"""Regression tests for review findings (round 1)."""
import asyncio

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)


def test_memory_plane_lease_kept_alive():
    """create_local runtimes must NOT self-destruct at the 10s lease TTL."""
    import dynamo_tpu.runtime.distributed as dist

    async def main():
        old = dist.LEASE_TTL_S
        dist.LEASE_TTL_S = 0.3
        try:
            plane = MemoryPlane()
            rt = await DistributedRuntime.create_local(plane, "w")
            await rt.kv.put("k", b"v", rt.lease.id)
            await asyncio.sleep(1.2)  # 4x TTL
            assert not rt.shutdown_event.is_set()
            assert await rt.kv.get("k") == b"v"
            await rt.shutdown()
            await asyncio.sleep(0.05)
            assert await rt.kv.get("k") is None  # revoke removed the key
        finally:
            dist.LEASE_TTL_S = old

    asyncio.run(main())


def test_engine_rejection_propagates_to_client():
    async def main():
        plane = MemoryPlane()
        srt = await DistributedRuntime.create_local(plane, "w")

        def bad_engine(request, context):
            raise ValueError("bad request shape")

        await srt.namespace("ns").component("c").endpoint("gen").serve(bad_engine)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        with pytest.raises(RuntimeError, match="bad request shape"):
            async for _ in await client.generate({}):
                pass
        await crt.shutdown()
        await srt.shutdown()

    asyncio.run(main())


def test_duplicate_page_hash_no_leak():
    """Two requests computing identical pages must not leak pool pages."""
    eng = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=32, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512), seed=0)
    prompt = list(range(1, 25))  # 3 full pages
    p = SamplingParams(max_tokens=2, temperature=0.0)
    # run both CONCURRENTLY so neither can prefix-hit the other's pages
    eng.add_request(EngineRequest("a", prompt, p))
    eng.add_request(EngineRequest("b", prompt, p))
    done = set()
    while len(done) < 2:
        for ev in eng.step():
            if ev.finished:
                done.add(ev.request_id)
    alloc = eng.scheduler.allocator
    assert alloc.num_free == alloc.num_pages  # everything reclaimable


def test_min_tokens_blocks_eos():
    eng0 = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512), seed=0)
    prompt = list(range(10, 26))
    ref = eng0.generate(prompt, SamplingParams(max_tokens=8), "probe")
    eos = ref[2]

    def eng_with_eos():
        return NativeEngine(
            CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                              max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                              max_model_len=512),
            eos_token_ids={eos}, seed=0)

    # without min_tokens: stops at the eos position, eos not emitted
    out = eng_with_eos().generate(prompt, SamplingParams(max_tokens=8), "x")
    assert len(out) == 2
    # with min_tokens: eos masked, generation continues past it
    out2 = eng_with_eos().generate(
        prompt, SamplingParams(max_tokens=6, min_tokens=5), "y")
    assert len(out2) >= 5
    assert eos not in out2[:4]


def test_preemption_preserves_greedy_output():
    """Force preemption via a tiny page pool; greedy outputs must match an
    un-preempted engine, and max_tokens must be respected."""
    gen_cfg = dict(page_size=8, max_slots=2, max_prefill_chunk=16,
                   prefill_buckets=(8, 16), max_model_len=256)
    big = NativeEngine(CFG, EngineConfig(num_pages=64, **gen_cfg), seed=0)
    p = SamplingParams(max_tokens=12, temperature=0.0)
    prompts = [list(range(3, 19)), list(range(40, 56))]
    expect = [big.generate(pr, p, f"s{i}") for i, pr in enumerate(prompts)]

    # 8 pages of 8 tokens = 64 token slots; two seqs of 16+12=28 tokens need
    # 56 slots but page-granularity rounding forces contention/preemption.
    small = NativeEngine(CFG, EngineConfig(num_pages=8, **gen_cfg), seed=0)
    for i, pr in enumerate(prompts):
        small.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(2)}
    done = set()
    for _ in range(500):
        for ev in small.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
        if len(done) == 2:
            break
    assert len(done) == 2, "requests did not finish under memory pressure"
    assert [got[f"r{i}"] for i in range(2)] == expect


def test_preempt_readmit_invalidates_device_decode_state():
    """A request preempted and re-prefilled between decode windows must not
    match the cached device decode-state signature: same request_id, same
    slot, same page COUNT (single page here), but the device-side token/
    position/page-table are stale (code-review r3). The admission epoch in
    the sig forces a rebuild; greedy output must equal an undisturbed run."""
    cfg = EngineConfig(page_size=64, num_pages=8, max_slots=2,
                       max_prefill_chunk=16, prefill_buckets=(8, 16),
                       max_model_len=128, decode_steps=4)
    prompt = list(range(5, 13))
    p = SamplingParams(max_tokens=12, temperature=0.0)
    expect = NativeEngine(CFG, cfg, seed=0).generate(prompt, p, "ref")

    eng = NativeEngine(CFG, cfg, seed=0)
    eng.add_request(EngineRequest("r", prompt, p))
    got = []
    preempted = False
    for _ in range(60):
        for ev in eng.step():
            if ev.token is not None:
                got.append(ev.token)
            if ev.finished:
                break
        else:
            # after the first decode WINDOW (prefill emits 1 token, the
            # window adds decode_steps more): forcibly preempt the running
            # seq (the memory-pressure path self-evicts exactly like this).
            # Preempting earlier would miss the bug — _dec_state is only
            # populated once a decode window has run.
            if not preempted and len(got) > cfg.decode_steps:
                eng.scheduler._preempt_one()
                preempted = True
            continue
        break
    assert preempted
    # re-prefill recomputes the KV; tokens already streamed must not be
    # re-streamed, and the continuation must match the undisturbed run
    assert got == expect
