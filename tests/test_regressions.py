"""Regression tests for review findings (round 1)."""
import asyncio

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)


def test_memory_plane_lease_kept_alive():
    """create_local runtimes must NOT self-destruct at the 10s lease TTL."""
    import dynamo_tpu.runtime.distributed as dist

    async def main():
        old = dist.LEASE_TTL_S
        dist.LEASE_TTL_S = 0.3
        try:
            plane = MemoryPlane()
            rt = await DistributedRuntime.create_local(plane, "w")
            await rt.kv.put("k", b"v", rt.lease.id)
            await asyncio.sleep(1.2)  # 4x TTL
            assert not rt.shutdown_event.is_set()
            assert await rt.kv.get("k") == b"v"
            await rt.shutdown()
            await asyncio.sleep(0.05)
            assert await rt.kv.get("k") is None  # revoke removed the key
        finally:
            dist.LEASE_TTL_S = old

    asyncio.run(main())


def test_engine_rejection_propagates_to_client():
    async def main():
        plane = MemoryPlane()
        srt = await DistributedRuntime.create_local(plane, "w")

        def bad_engine(request, context):
            raise ValueError("bad request shape")

        await srt.namespace("ns").component("c").endpoint("gen").serve(bad_engine)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        with pytest.raises(RuntimeError, match="bad request shape"):
            async for _ in await client.generate({}):
                pass
        await crt.shutdown()
        await srt.shutdown()

    asyncio.run(main())


def test_duplicate_page_hash_no_leak():
    """Two requests computing identical pages must not leak pool pages."""
    eng = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=32, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512), seed=0)
    prompt = list(range(1, 25))  # 3 full pages
    p = SamplingParams(max_tokens=2, temperature=0.0)
    # run both CONCURRENTLY so neither can prefix-hit the other's pages
    eng.add_request(EngineRequest("a", prompt, p))
    eng.add_request(EngineRequest("b", prompt, p))
    done = set()
    while len(done) < 2:
        for ev in eng.step():
            if ev.finished:
                done.add(ev.request_id)
    alloc = eng.scheduler.allocator
    assert alloc.num_free == alloc.num_pages  # everything reclaimable


def test_min_tokens_blocks_eos():
    eng0 = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512), seed=0)
    prompt = list(range(10, 26))
    ref = eng0.generate(prompt, SamplingParams(max_tokens=8), "probe")
    eos = ref[2]

    def eng_with_eos():
        return NativeEngine(
            CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                              max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                              max_model_len=512),
            eos_token_ids={eos}, seed=0)

    # without min_tokens: stops at the eos position, eos not emitted
    out = eng_with_eos().generate(prompt, SamplingParams(max_tokens=8), "x")
    assert len(out) == 2
    # with min_tokens: eos masked, generation continues past it
    out2 = eng_with_eos().generate(
        prompt, SamplingParams(max_tokens=6, min_tokens=5), "y")
    assert len(out2) >= 5
    assert eos not in out2[:4]


def test_preemption_preserves_greedy_output():
    """Force preemption via a tiny page pool; greedy outputs must match an
    un-preempted engine, and max_tokens must be respected."""
    gen_cfg = dict(page_size=8, max_slots=2, max_prefill_chunk=16,
                   prefill_buckets=(8, 16), max_model_len=256)
    big = NativeEngine(CFG, EngineConfig(num_pages=64, **gen_cfg), seed=0)
    p = SamplingParams(max_tokens=12, temperature=0.0)
    prompts = [list(range(3, 19)), list(range(40, 56))]
    expect = [big.generate(pr, p, f"s{i}") for i, pr in enumerate(prompts)]

    # 8 pages of 8 tokens = 64 token slots; two seqs of 16+12=28 tokens need
    # 56 slots but page-granularity rounding forces contention/preemption.
    small = NativeEngine(CFG, EngineConfig(num_pages=8, **gen_cfg), seed=0)
    for i, pr in enumerate(prompts):
        small.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(2)}
    done = set()
    for _ in range(500):
        for ev in small.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
        if len(done) == 2:
            break
    assert len(done) == 2, "requests did not finish under memory pressure"
    assert [got[f"r{i}"] for i in range(2)] == expect


def test_preempt_readmit_invalidates_device_decode_state():
    """A request preempted and re-prefilled between decode windows must not
    match the cached device decode-state signature: same request_id, same
    slot, same page COUNT (single page here), but the device-side token/
    position/page-table are stale (code-review r3). The admission epoch in
    the sig forces a rebuild; greedy output must equal an undisturbed run."""
    cfg = EngineConfig(page_size=64, num_pages=8, max_slots=2,
                       max_prefill_chunk=16, prefill_buckets=(8, 16),
                       max_model_len=128, decode_steps=4)
    prompt = list(range(5, 13))
    p = SamplingParams(max_tokens=12, temperature=0.0)
    expect = NativeEngine(CFG, cfg, seed=0).generate(prompt, p, "ref")

    eng = NativeEngine(CFG, cfg, seed=0)
    eng.add_request(EngineRequest("r", prompt, p))
    got = []
    preempted = False
    for _ in range(60):
        for ev in eng.step():
            if ev.token is not None:
                got.append(ev.token)
            if ev.finished:
                break
        else:
            # after the first decode WINDOW (prefill emits 1 token, the
            # window adds decode_steps more): forcibly preempt the running
            # seq (the memory-pressure path self-evicts exactly like this).
            # Preempting earlier would miss the bug — _dec_state is only
            # populated once a decode window has run.
            if not preempted and len(got) > cfg.decode_steps:
                eng.scheduler._preempt_one()
                preempted = True
            continue
        break
    assert preempted
    # re-prefill recomputes the KV; tokens already streamed must not be
    # re-streamed, and the continuation must match the undisturbed run
    assert got == expect


# -- round 5: NaN page poisoning through recycled KV pages ---------------------

def _tiny_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


def test_oov_token_ids_rejected_at_admission():
    """An out-of-vocab token id silently becomes NaN at the embedding
    gather (jnp.take fills OOB reads) and the NaN KV then poisons future
    tenants of the freed pages. The engine must refuse such requests
    with a clean ValueError (the worker turns it into an error frame)
    instead of serving garbage. Found by the chaos harness: a request
    completed with another request's degenerate argmax-0 tokens."""
    eng = _tiny_engine()
    bad = [3, 4, CFG.vocab_size + 10, 5]
    with pytest.raises(ValueError, match="vocab"):
        eng.add_request(EngineRequest("bad", bad, SamplingParams()))
    # remote-allocation path validates too
    with pytest.raises(ValueError, match="vocab"):
        eng.allocate_remote(EngineRequest("bad2", bad, SamplingParams()))


def test_nonfinite_recycled_pages_never_poison_requests():
    """Defense in depth for the same failure class when NaN/Inf enters
    the cache anyway (bf16 overflow on a real model, a buggy transfer):
    masked attention must zero invalid V rows, because a 0-probability
    times a NaN V row is NaN (IEEE), which rides into the logits and
    collapses the argmax. Poison the ENTIRE cache; a fresh request only
    ever reads its own written rows, so its output must match a clean
    engine exactly — prefill (stale rows beyond kv_len inside the
    page-table bucket) and decode windows (stale base-buffer tail) both
    exercise the masked path."""
    import jax.numpy as jnp

    prompt = list(range(100, 120))
    p = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)
    expect = _tiny_engine().generate(prompt, p, "clean")

    eng = _tiny_engine()
    eng.cache = {"k": jnp.full_like(eng.cache["k"], jnp.nan),
                 "v": jnp.full_like(eng.cache["v"], jnp.nan)}
    got = eng.generate(prompt, p, "poisoned")
    assert got == expect


def test_oov_rejection_remote_path_emits_error_frame():
    """The disagg remote path must surface an admission rejection as the
    same per-request ERROR frame the local path emits, not kill the
    stream with an unhandled ValueError (code-review r5)."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.protocols.common import (
        FinishReason, PreprocessedRequest, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    bad_prompt = [3] * 19 + [CFG.vocab_size + 7]  # long => routed remote

    async def main():
        plane = MemoryPlane()
        transfer = LocalTransferBackend()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=4, model="tiny")
        decode = DisaggDecodeWorker(_tiny_engine(), plane.messaging,
                                    router, queue, worker_id="dec-0",
                                    prefill_timeout_s=10.0)
        transfer.register("dec-0", decode)
        prefill = PrefillWorker(NativeEngineWorker(_tiny_engine()), queue,
                                transfer, plane.messaging)
        await decode.start()
        await prefill.start()
        try:
            req = PreprocessedRequest(
                request_id="bad", token_ids=bad_prompt,
                stop=StopConditions(max_tokens=4, ignore_eos=True))
            frames = []
            async for frame in decode.generate(
                    req.model_dump(exclude_none=True), Context("bad")):
                frames.append(frame)
        finally:
            await prefill.stop()
            await decode.stop()
        return frames

    frames = asyncio.run(main())
    assert frames, "no frames at all"
    assert frames[-1]["finish_reason"] == FinishReason.ERROR.value
    assert "vocab" in frames[-1].get("text", "")


def test_completed_id_reuse_never_resumes_stale_device_state():
    """A new request REUSING a finished request's id (stable client ids,
    retried jobs) must decode from ITS OWN prefill, not the dead
    request's device-resident carry. Before the per-admission epoch
    (scheduler._epoch_seq), both admissions keyed the decode-state
    signature as (id, epoch=0); with the same slot and page count the
    stale signature matched and the engine fed the finished request's
    final (token, position, counter) device arrays back in — silently
    wrong tokens from position 1 on (found by the integrity tests
    sharing an oracle engine across scenarios)."""
    gen_cfg = dict(page_size=8, num_pages=64, max_slots=4,
                   max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                   max_model_len=512)
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    # same lengths => same page counts => identical sig apart from epoch
    p1, p2 = list(range(100, 120)), list(range(40, 60))
    expect = NativeEngine(CFG, EngineConfig(**gen_cfg),
                          seed=0).generate(p2, params, "fresh")

    eng = NativeEngine(CFG, EngineConfig(**gen_cfg), seed=0)
    eng.generate(p1, params, "stable-id")
    assert eng.generate(p2, params, "stable-id") == expect
    # and a third reuse, now with p2's pages warm in the prefix cache
    assert eng.generate(p2, params, "stable-id") == expect
