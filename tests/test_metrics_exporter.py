"""Standalone metrics exporter tests (VERDICT r2 next #9).

The 'Done' bar: the exporter serves llm_kv_blocks_* for a 2-worker graph.
Reference: components/metrics binary, components/metrics/src/lib.rs:96-616.
"""
import asyncio

from dynamo_tpu.kv_router.publisher import KV_HIT_RATE_SUBJECT
from dynamo_tpu.observability.exporter import MetricsExporter
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane


async def fake_engine(request, context):
    yield {"ok": True}


def test_exporter_two_worker_graph():
    async def main():
        plane = MemoryPlane()
        rts = []
        # w0 also reports decode-pipeline occupancy, through a mutable
        # dict so the test can advance it mid-run (what a live engine's
        # step loop does) and assert the gauges follow
        pipe_stats = {"decode_windows": 4, "pipeline_windows": 3,
                      "pipeline_overlapped": 2, "pipeline_fallbacks": 1,
                      "decode_host_syncs": 4, "decode_plan_uploads": 1}
        for i, (active, total) in enumerate(((3, 16), (5, 16))):
            rt = await DistributedRuntime.create_local(plane, f"w{i}")
            ep = rt.namespace("ns").component("worker").endpoint("generate")
            extra = pipe_stats if i == 0 else {}
            await ep.serve(
                fake_engine,
                stats_handler=lambda a=active, t=total, e=extra: {
                    "request_active_slots": 1, "request_total_slots": 4,
                    "kv_active_blocks": a, "kv_total_blocks": t,
                    "num_requests_waiting": 0,
                    "gpu_cache_usage_perc": a / t,
                    "gpu_prefix_cache_hit_rate": 0.5, **e})
            rts.append(rt)

        ert = await DistributedRuntime.create_local(plane, "exporter")
        exporter = MetricsExporter(ert, "ns", "worker", port=0,
                                   scrape_interval_s=0.05)
        await exporter.start()
        try:
            # router hit-rate event rides the component event plane
            await rts[0].namespace("ns").component("router").publish(
                KV_HIT_RATE_SUBJECT,
                {"worker_id": "w0", "isl_blocks": 8, "overlap_blocks": 6})
            await asyncio.sleep(0.3)  # a few scrape cycles

            reader, writer = await asyncio.open_connection(
                "127.0.0.1", exporter.port)
            writer.write(b"GET /metrics HTTP/1.1\r\nhost: x\r\n\r\n")
            await writer.drain()
            raw = await reader.read(65536)
            writer.close()
            body = raw.decode()
            assert "200 OK" in body
            assert 'llm_kv_blocks_active{worker="w0"} 3' in body
            assert 'llm_kv_blocks_active{worker="w1"} 5' in body
            assert 'llm_kv_blocks_total{worker="w0"} 16' in body
            assert "llm_workers 2" in body
            assert "llm_load_avg 4" in body
            assert "llm_router_kv_hit_rate 0.75" in body
            # decode-pipeline occupancy gauges (overlap counters)
            assert 'llm_decode_windows{worker="w0"} 4' in body
            assert 'llm_decode_pipeline_overlapped{worker="w0"} 2' in body
            assert 'llm_decode_pipeline_fallbacks{worker="w0"} 1' in body
            assert 'llm_decode_plan_uploads{worker="w0"} 1' in body
            # the engine keeps committing overlapped windows: the gauges
            # must ADVANCE with the next scrape
            pipe_stats.update(decode_windows=11, pipeline_windows=10,
                              pipeline_overlapped=9, decode_host_syncs=10)

            # reliability counter snapshots ride the event plane the same
            # way ({ns}.{source}.reliability) and fold into gauges labeled
            # by the publishing frontend
            from dynamo_tpu.frontend.reliability import ReliabilityMetrics
            rm = ReliabilityMetrics()
            rm.migrations.inc(value=3)
            rm.retries.inc(value=2)
            rm.breaker_opens.inc()
            rm.shed_requests.inc(value=5)
            rm.stall_fires.inc()
            await rm.publish(rts[0].namespace("ns").component("front0"))
            await asyncio.sleep(0.2)

            # a worker going away drops its series
            await rts[1].shutdown()
            await asyncio.sleep(0.3)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", exporter.port)
            writer.write(b"GET /metrics HTTP/1.1\r\n\r\n")
            await writer.drain()
            body2 = (await reader.read(65536)).decode()
            writer.close()
            assert 'llm_kv_blocks_active{worker="w1"}' not in body2
            assert "llm_workers 1" in body2
            assert 'llm_decode_windows{worker="w0"} 11' in body2
            assert 'llm_decode_pipeline_overlapped{worker="w0"} 9' in body2
            assert 'llm_decode_host_syncs{worker="w0"} 10' in body2
            assert 'llm_reliability_migrations{source="front0"} 3' in body2
            assert 'llm_reliability_retries{source="front0"} 2' in body2
            assert 'llm_reliability_breaker_opens{source="front0"} 1' \
                in body2
            assert 'llm_reliability_breaker_closes{source="front0"} 0' \
                in body2
            assert 'llm_reliability_shed_requests{source="front0"} 5' \
                in body2
            assert 'llm_reliability_stall_fires{source="front0"} 1' in body2
            assert 'llm_reliability_deadline_exceeded{source="front0"} 0' \
                in body2
            # control-plane gauges (runtime/cpstats.py CP_STATS), folded
            # at render: the exporter's own Client watch feeds them, and
            # a synthetic bump must be visible on the next scrape
            from dynamo_tpu.runtime.cpstats import CP_STATS
            assert "llm_cp_watch_queue_depth" in body2
            assert "llm_cp_router_degraded" in body2
            CP_STATS.indexer_nodes = 12345
            CP_STATS.router_degraded = 1
            CP_STATS.event_lag_seconds = 2.5
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", exporter.port)
            writer.write(b"GET /metrics HTTP/1.1\r\n\r\n")
            await writer.drain()
            body3 = (await reader.read(65536)).decode()
            writer.close()
            assert "llm_cp_indexer_nodes 12345" in body3
            assert "llm_cp_router_degraded 1" in body3
            assert "llm_cp_event_lag_seconds 2.5" in body3
            CP_STATS.reset()
        finally:
            await exporter.stop()
            for rt in rts:
                await rt.shutdown()
            await ert.shutdown()

    asyncio.run(main())


def _series_count(exporter) -> int:
    """Total live label series across every per-worker gauge family."""
    return sum(len(g._values) for g in exporter._worker_gauges())


def test_exporter_series_lifecycle_under_rolling_restart_churn():
    """Satellite (ISSUE 10): departed workers' per-instance series are
    remove()d at WATCH-EVENT time (the kv_router on_instance eviction,
    mirrored), so a rolling restart of uniquely-named workers cannot
    grow the exporter's series set without bound — and the eviction
    does NOT wait for the next scrape cycle."""
    async def main():
        plane = MemoryPlane()
        ert = await DistributedRuntime.create_local(plane, "exporter")
        # slow scrape interval: eviction must come from the watch path,
        # not from a lucky scrape landing in the sleep below
        exporter = MetricsExporter(ert, "ns", "worker", port=0,
                                   scrape_interval_s=30.0)
        await exporter.start()
        counts = []
        try:
            for gen in range(3):       # 3 generations of 2 workers each
                rts = []
                for i in range(2):
                    rt = await DistributedRuntime.create_local(
                        plane, f"gen{gen}-w{i}")
                    ep = rt.namespace("ns").component(
                        "worker").endpoint("generate")
                    await ep.serve(
                        fake_engine,
                        stats_handler=lambda: {
                            "request_active_slots": 1,
                            "request_total_slots": 4,
                            "kv_active_blocks": 2, "kv_total_blocks": 16,
                            "num_requests_waiting": 0,
                            "gpu_cache_usage_perc": 0.1,
                            "gpu_prefix_cache_hit_rate": 0.5})
                    rts.append(rt)
                await asyncio.sleep(0.05)      # watch puts land
                await exporter._aggregator.scrape_once()
                counts.append(_series_count(exporter))
                for rt in rts:                 # the whole generation dies
                    await rt.shutdown()
                await asyncio.sleep(0.05)      # watch DELETES land
                # no scrape between death and this check: the watch
                # listener alone must have evicted the series
                counts.append(_series_count(exporter))
            return counts
        finally:
            await exporter.stop()
            await ert.shutdown()

    counts = asyncio.run(main())
    alive, dead = counts[0::2], counts[1::2]
    # every generation renders the same bounded series count while
    # alive, and zero per-worker series after its delete events apply
    assert all(c == alive[0] > 0 for c in alive), counts
    assert all(c == 0 for c in dead), counts
