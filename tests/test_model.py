"""Model-level tests: paged attention correctness against dense oracles.

Strategy mirrors the reference's hardware-independent unit tests (SURVEY.md
§4.5): tiny configs, CPU devices, exact comparisons where possible.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import AttnMetadata
from dynamo_tpu.ops.attention import (
    dense_causal_attention, paged_attention, write_kv_pages,
)

CFG = ModelConfig(dtype="float32")  # f32 on CPU for tight comparisons


def test_paged_attention_matches_dense():
    """Scatter KV into shuffled pages; paged attn must equal dense attn."""
    rng = np.random.default_rng(0)
    b, t, h, hkv, hd, ps = 2, 48, 4, 2, 16, 8
    n_pages = 32
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, hkv, hd)), jnp.float32)

    # assign each sequence non-contiguous pages
    perm = rng.permutation(n_pages)
    pages_per_seq = t // ps
    page_table = np.zeros((b, pages_per_seq + 2), np.int32)  # padded bucket
    k_cache = jnp.zeros((hkv, n_pages, ps, hd), jnp.float32)
    v_cache = jnp.zeros((hkv, n_pages, ps, hd), jnp.float32)
    for i in range(b):
        pages = perm[i * pages_per_seq:(i + 1) * pages_per_seq]
        page_table[i, :pages_per_seq] = pages
        write_idx = np.array([pages[p // ps] * ps + p % ps for p in range(t)],
                             np.int32)[None, :]
        k_cache, v_cache = write_kv_pages(
            k_cache, v_cache, k[i:i + 1], v[i:i + 1], jnp.asarray(write_idx))

    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    kv_lens = jnp.full((b,), t, jnp.int32)
    out = paged_attention(q, k_cache, v_cache, jnp.asarray(page_table),
                          kv_lens, positions)
    expected = dense_causal_attention(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_write_kv_pages_drops_negative_indices():
    k_cache = jnp.zeros((1, 2, 4, 8), jnp.float32)
    v_cache = jnp.zeros((1, 2, 4, 8), jnp.float32)
    k_new = jnp.ones((1, 3, 1, 8), jnp.float32)
    write_idx = jnp.asarray([[0, -1, 5]], jnp.int32)
    k2, _ = write_kv_pages(k_cache, v_cache, k_new, k_new, write_idx)
    flat = np.asarray(k2).reshape(8, 8)
    assert flat[0].sum() == 8 and flat[5].sum() == 8
    assert np.abs(flat[[1, 2, 3, 4, 6, 7]]).sum() == 0


def _full_forward_logits(params, cfg, tokens_np):
    """Oracle: one prefill pass over the whole sequence, all positions."""
    t = len(tokens_np)
    ps = 8
    n_pages = (t + ps - 1) // ps + 1
    cache = llama.init_cache(cfg, n_pages, ps)
    meta = AttnMetadata(
        positions=jnp.arange(t, dtype=jnp.int32)[None],
        page_table=jnp.arange(n_pages, dtype=jnp.int32)[None],
        kv_lens=jnp.asarray([t], jnp.int32),
        write_idx=jnp.arange(t, dtype=jnp.int32)[None],
    )
    logits, _ = llama.forward(params, cfg, jnp.asarray(tokens_np)[None], cache, meta)
    return np.asarray(logits[0])


def test_chunked_prefill_and_decode_match_full_forward():
    """KV built incrementally (chunks + single-token decode) must give the
    same logits as one full-sequence pass."""
    cfg = CFG
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(2)
    t = 20
    tokens = rng.integers(0, cfg.vocab_size, t).astype(np.int32)
    full = _full_forward_logits(params, cfg, tokens)

    ps = 8
    n_pages = 8
    cache = llama.init_cache(cfg, n_pages, ps)
    page_table = jnp.arange(n_pages, dtype=jnp.int32)[None]
    got = np.zeros_like(full)
    # chunked prefill: [0,8), [8,16)
    for start, end in [(0, 8), (8, 16)]:
        meta = AttnMetadata(
            positions=jnp.arange(start, end, dtype=jnp.int32)[None],
            page_table=page_table,
            kv_lens=jnp.asarray([end], jnp.int32),
            write_idx=jnp.arange(start, end, dtype=jnp.int32)[None],
        )
        logits, cache = llama.forward(
            params, cfg, jnp.asarray(tokens[start:end])[None], cache, meta)
        got[start:end] = np.asarray(logits[0])
    # decode one token at a time: positions 16..19
    for pos in range(16, t):
        meta = AttnMetadata(
            positions=jnp.asarray([[pos]], jnp.int32),
            page_table=page_table,
            kv_lens=jnp.asarray([pos + 1], jnp.int32),
            write_idx=jnp.asarray([[pos]], jnp.int32),
        )
        logits, cache = llama.forward(
            params, cfg, jnp.asarray([[tokens[pos]]]), cache, meta)
        got[pos] = np.asarray(logits[0, 0])

    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-4)


def test_moe_forward_runs():
    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    logits = _full_forward_logits(params, cfg, np.arange(10, dtype=np.int32))
    assert logits.shape == (10, cfg.vocab_size)
    assert np.isfinite(logits).all()


def test_moe_dispatch_matches_dense_compute():
    """Capacity dispatch (EP path) == dense-compute oracle when nothing is
    dropped (capacity_factor = E guarantees room for any routing)."""
    import dataclasses

    from dynamo_tpu.ops.moe import moe_dispatch_mlp

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2, moe_capacity_factor=4.0)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0 weights
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 12, cfg.hidden_size)),
                    jnp.float32)
    dense = llama._moe_mlp(x, lp, cfg)
    disp = moe_dispatch_mlp(x, lp, cfg, capacity_factor=float(cfg.num_experts))
    np.testing.assert_allclose(np.asarray(disp), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_moe_dispatch_drop_accounting():
    """Forced routing imbalance: the (dropped, routed) counters are exact.

    ADVICE r1 (medium): GShard capacity dispatch drops tokens silently;
    the counters make the degradation observable."""
    from dynamo_tpu.ops.moe import moe_dispatch_mlp

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    lp = dict(jax.tree.map(lambda a: a[0], params["layers"]))
    t, k, e = 16, cfg.num_experts_per_tok, cfg.num_experts
    rng = np.random.default_rng(5)
    x_np = rng.standard_normal((1, t, cfg.hidden_size)).astype(np.float32)
    out, (dropped, routed) = moe_dispatch_mlp(
        jnp.asarray(x_np), lp, cfg, capacity_factor=0.25,
        return_dropped=True)
    # numpy replication of the routing + capacity accounting
    logits = x_np[0] @ np.asarray(lp["router"], np.float32)       # [t, e]
    top2 = np.argsort(-logits, axis=-1, kind="stable")[:, :k]     # [t, k]
    cap = max(int(t * k / e * 0.25), 1)                           # 2
    counts = np.zeros(e, np.int64)
    kept = 0
    for tok in range(t):                  # token-major order, like cumsum
        for c in range(k):
            ex = top2[tok, c]
            if counts[ex] < cap:
                kept += 1
            counts[ex] += 1
    assert int(routed) == t * k
    assert int(dropped) == t * k - kept
    assert int(dropped) > 0, "capacity 0.25 must actually drop"
    assert np.isfinite(np.asarray(out)).all()


def test_moe_dispatch_parity_and_no_drops_at_shipped_capacity():
    """At the shipped capacity_factor=2.0 with near-balanced routing the
    dispatch path matches the dense oracle exactly and drops nothing —
    the parity coverage ADVICE r1 flagged as missing for the serving
    default."""
    from dynamo_tpu.ops.moe import moe_dispatch_mlp

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2, moe_capacity_factor=2.0)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((2, 24, cfg.hidden_size)),
                    jnp.float32)
    disp, (dropped, _) = moe_dispatch_mlp(
        x, lp, cfg, capacity_factor=cfg.moe_capacity_factor,
        return_dropped=True)
    assert int(dropped) == 0, (
        "seeded routing should stay under capacity at the shipped factor")
    dense = llama._moe_mlp(x, lp, cfg)
    np.testing.assert_allclose(np.asarray(disp), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_moe_engine_surfaces_drop_counters():
    """Engine-level: a dispatch-MoE engine accumulates routed/dropped."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2, max_model_len=128)
    ecfg = EngineConfig(page_size=8, num_pages=16, max_slots=2,
                        max_prefill_chunk=16, prefill_buckets=(8, 16),
                        max_model_len=128)
    eng = NativeEngine(cfg, ecfg, seed=0)
    out = eng.generate(list(range(10)),
                       SamplingParams(max_tokens=3, ignore_eos=True), "m")
    assert len(out) == 3
    assert eng.moe_routed_tokens > 0
    assert 0.0 <= eng.moe_drop_rate() <= 1.0


def test_moe_dispatch_sharded_over_ep_mesh():
    """Expert weights sharded over an ep mesh axis; jit compiles + matches."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.ops.moe import moe_dispatch_mlp
    from dynamo_tpu.parallel.mesh import make_mesh

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    mesh = make_mesh(ep=4, tp=2)
    shard = {
        "router": NamedSharding(mesh, P(None, None)),
        "w_gate": NamedSharding(mesh, P("ep", None, "tp")),
        "w_up": NamedSharding(mesh, P("ep", None, "tp")),
        "w_down": NamedSharding(mesh, P("ep", "tp", None)),
    }
    lp_sh = {k: (jax.device_put(v, shard[k]) if k in shard else v)
             for k, v in lp.items()}
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((1, 16, cfg.hidden_size)),
                    jnp.float32)
    ref = moe_dispatch_mlp(x, lp, cfg, capacity_factor=4.0)
    got = jax.jit(lambda a, w: moe_dispatch_mlp(a, w, cfg, 4.0))(x, lp_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_moe_dispatch_sharded_shard_map_matches_and_bounds_memory():
    """The explicit shard_map EP dispatch (O(E/ep) per-shard buffers,
    VERDICT r2 next #7) matches the dense dispatch, keeps drop accounting,
    and its compiled per-shard dispatch tensors carry only E/ep experts."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamo_tpu.ops.moe import moe_dispatch_mlp, moe_dispatch_mlp_sharded
    from dynamo_tpu.parallel.mesh import make_mesh

    cfg = ModelConfig(name="tiny-moe", dtype="float32", num_experts=4,
                      num_experts_per_tok=2)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    mesh = make_mesh(ep=4, tp=2)
    shard = {
        "router": NamedSharding(mesh, P(None, None)),
        "w_gate": NamedSharding(mesh, P("ep", None, "tp")),
        "w_up": NamedSharding(mesh, P("ep", None, "tp")),
        "w_down": NamedSharding(mesh, P("ep", "tp", None)),
    }
    lp_sh = {k: (jax.device_put(v, shard[k]) if k in shard else v)
             for k, v in lp.items()}
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((1, 16, cfg.hidden_size)),
                    jnp.float32)
    ref, (drop_ref, routed_ref) = moe_dispatch_mlp(
        x, lp, cfg, capacity_factor=2.0, return_dropped=True)
    fn = jax.jit(lambda a, w: moe_dispatch_mlp_sharded(
        a, w, cfg, mesh, 2.0, return_dropped=True))
    got, (drop, routed) = fn(x, lp_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    assert float(drop) == float(drop_ref)
    assert float(routed) == float(routed_ref)
    # compiled-HLO check: no per-shard buffer carries the FULL expert dim
    # with a capacity axis — dispatch/combine must be [_, S, E/ep, C]
    txt = fn.lower(x, lp_sh).compile().as_text()
    s_tok, e, cap = 16 * 2, 4, 16  # S = T*k; cap = T*k/E*2.0
    full = f"{s_tok},{e},{cap}"      # what the dense path would allocate
    local = f"{s_tok},{e // 4},{cap}"
    assert local.lower() in txt.lower().replace(" ", ""), "local dispatch missing"
    assert full.lower() not in txt.lower().replace(" ", ""), \
        "full-expert capacity buffer present on a shard"
