"""Fleet rollup over a live (small) simcluster — the scrape-loop leg
of the telemetry plane. The full 64-worker storm with SLO fire->clear
is the committed FLEET_r10.json evidence (tools/fleet_storm.py,
golden-checked in test_telemetry.py); this smoke keeps the $STATS
scrape -> series -> summary -> watchdog wiring honest at tier-1 cost
(8 workers, a handful of scrapes, no sleeps beyond sim startup).
"""
import asyncio

import pytest

from dynamo_tpu.observability.fleet import FleetRollup, TransferCostModel
from dynamo_tpu.observability.slo import SloSpec, SloWatchdog
from dynamo_tpu.observability.timeseries import SeriesStore
from dynamo_tpu.runtime.cpstats import CP_STATS
from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig


@pytest.fixture(autouse=True)
def clean_cp_state():
    CP_STATS.reset()
    yield
    CP_STATS.reset()


def test_rollup_scrapes_sim_fleet_into_series_and_summary():
    async def main():
        sim = await SimCluster(SimConfig(workers=8, streams=64,
                                         seed=3)).start()
        model = TransferCostModel()
        store = SeriesStore(interval_s=1.0, capacity=64)
        rollup = FleetRollup(sim.client, store=store, interval_s=1.0,
                             model=model, expected_workers=8)
        try:
            # seeded per-link bandwidth samples (a live fleet feeds
            # these from the transfer backends)
            model.observe("w0000", 10_000_000, 0.01)
            model.observe("w0001", 2_000_000, 0.01)
            for t in (100.0, 101.0, 102.0):
                snap = await rollup.scrape_once(ts=t)
            return snap, store, rollup.summary(window_s=5.0, ts=102.0), sim
        finally:
            await sim.stop()

    snap, store, summary, sim = asyncio.run(main())
    assert snap["workers"] == 8
    assert snap["links"] == 2
    # per-worker history for every rollup field, incl. the synthetic
    # ledger figures the sim workers publish
    assert store.get("worker/w0003/kv_active_blocks") is not None
    assert store.get("worker/w0003/engine_tok_s").latest() > 0
    # fleet aggregates
    assert store.get("fleet/workers_live").window(5.0, 102.0) == [8.0] * 3
    assert store.get("fleet/availability").latest() == 1.0
    assert store.get("fleet/tok_s_total").latest() > 0
    # link EWMAs surfaced as series
    assert store.get("link/w0000/bytes_per_s").latest() == \
        pytest.approx(1e9)
    # summary is the fleet_top/evidence shape
    assert summary["workers_seen"] == 8
    assert summary["fleet"]["availability"]["last"] == 1.0
    assert set(summary["links"]) == {"w0000", "w0001"}


def test_rollup_feeds_watchdog_availability_drop():
    """Kill half the sim fleet; the availability series the rollup
    records must take a bandwidth-floor-style SLO over threshold —
    the live half of what the seeded-plan test proves virtually."""
    async def main():
        sim = await SimCluster(SimConfig(workers=8, streams=64, seed=5,
                                         lease_ttl_s=0.5)).start()
        store = SeriesStore(interval_s=1.0, capacity=256)
        rollup = FleetRollup(sim.client, store=store, interval_s=1.0,
                             model=TransferCostModel(),
                             expected_workers=8)
        wd = SloWatchdog(store, [SloSpec(
            name="avail", series="fleet/availability", objective=0.7,
            mode="below", target=0.9, short_window_s=3.0,
            long_window_s=6.0, burn_threshold=2.0, min_samples=2)],
            degraded_fn=lambda: False)
        try:
            t = 100.0
            for _ in range(6):
                await rollup.scrape_once(ts=t)
                wd.evaluate(t)
                t += 1.0
            assert not wd.firing()
            targets = await sim.kill_fraction(fraction=0.5)
            fired_at = None
            for _ in range(8):
                await rollup.scrape_once(ts=t)
                if wd.evaluate(t) and wd.firing():
                    fired_at = t
                t += 1.0
            return targets, fired_at, wd.firing(), store
        finally:
            await sim.stop()

    targets, fired_at, firing, store = asyncio.run(main())
    assert len(targets) == 4
    assert store.get("fleet/availability").latest() == pytest.approx(0.5)
    assert firing == ["avail"]
    assert fired_at is not None


def test_rollup_per_role_aggregates_and_signals():
    """ISSUE 12 satellite: the rollup exposes the prefill/decode split
    directly (role/* series + per_role + summary.roles) so the
    autoscaler and fleet_top read one schema instead of re-deriving
    it; signals_from_rollup folds the same series into FleetSignals."""
    from dynamo_tpu.runtime.autoscaler import (
        ROLE_DECODE, ROLE_PREFILL, signals_from_rollup,
    )

    async def main():
        sim = await SimCluster(SimConfig(workers=8, streams=64,
                                         seed=4)).start()
        store = SeriesStore(interval_s=1.0, capacity=64)
        rollup = FleetRollup(sim.client, store=store, interval_s=1.0,
                             model=TransferCostModel(),
                             expected_workers=8)
        try:
            ids = sorted(sim.workers)
            for i, wid in enumerate(ids):
                await sim.workers[wid].assign_role(
                    ROLE_PREFILL if i < 5 else ROLE_DECODE)
            deadline = asyncio.get_running_loop().time() + 5.0
            while len(sim.client.ids_for_role(ROLE_PREFILL)) != 5:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            await rollup.scrape_once(ts=100.0)
            healthy = rollup.per_role()
            # one prefill worker starts draining: the role aggregates
            # see it at the next scrape (ready drops, draining counts)
            await sim.workers[ids[0]].mark_draining()
            while ids[0] in sim.client.ids_for_role(ROLE_PREFILL):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            await rollup.scrape_once(ts=101.0)
            sig = signals_from_rollup(rollup, None, ts=101.0)
            return healthy, rollup.per_role(), rollup.summary(
                window_s=5.0, ts=101.0), sig
        finally:
            await sim.stop()

    healthy, drained, summary, sig = asyncio.run(main())
    assert healthy[ROLE_PREFILL]["workers"] == 5
    assert healthy[ROLE_DECODE]["workers"] == 3
    assert healthy[ROLE_PREFILL]["availability"] == 1.0
    assert "queue_depth" in healthy[ROLE_PREFILL]
    assert "occupancy" in healthy[ROLE_DECODE]
    assert drained[ROLE_PREFILL]["workers"] == 4
    assert drained[ROLE_PREFILL]["draining"] == 1
    assert drained[ROLE_PREFILL]["availability"] == pytest.approx(0.8)
    # the summary carries the role block (fleet_top renders it)
    assert summary["roles"][ROLE_PREFILL]["workers"]["last"] == 4.0
    # and the controller-facing fold reads the same schema
    assert sig.roles[ROLE_PREFILL].workers == 4
    assert sig.roles[ROLE_PREFILL].draining == 1
    assert sig.roles[ROLE_DECODE].workers == 3
