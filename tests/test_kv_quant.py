"""Quantized KV cache (ops/kv_quant.py): int8 pages end-to-end.

Three bars, mirroring the PR's exactness contract:

- ``kv_quant=""`` (the default) never touches the codec — its exactness
  is enforced by the whole existing suite (test_mixed_steps /
  test_decode_pipeline / test_engine are the identity harness) staying
  token-identical through this refactor;
- ``kv_quant="int8"`` passes the COMMITTED parity gate — greedy-match
  rate >= bench.KVQ_MATCH_MIN against the unquantized twin plus bounded
  prefill-logit drift — via the same bench.run_kv_quant_parity the TPU
  ladder runs (tools/tpu_parity_quick.py, PARITY_TPU_r06_kvq);
- the int8 engine agrees with ITSELF across schedulers and pipeline
  depths (mixed vs alternating, depth 1 vs 2, mid-stream admissions):
  quantization changes values, never scheduling-dependent behavior.

Engines are module-scoped and reused (tier-1 budget); the alternating
oracle is the same engine with `scheduler.mixed_token_budget` flipped,
as in test_mixed_steps.
"""
import dataclasses

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams

CFG = ModelConfig(dtype="float32", max_model_len=512)

ENGINE_KW = dict(
    page_size=16, num_pages=64, max_slots=2, max_prefill_chunk=32,
    prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4)


@pytest.fixture(scope="module")
def eng_q():
    """The int8-KV engine: mixed steps on (default), pipeline depth 2."""
    return NativeEngine(CFG, EngineConfig(kv_quant="int8", pipeline_depth=2,
                                          **ENGINE_KW), seed=0)


# -- codec units ---------------------------------------------------------------

def test_codec_roundtrip_error_bound():
    from dynamo_tpu.ops.kv_quant import dequantize_rows, quantize_rows
    rng = np.random.RandomState(0)
    x = rng.randn(3, 5, 32).astype(np.float32) * 4.0
    q, s = quantize_rows(x)
    assert np.asarray(q).dtype == np.int8
    assert np.asarray(s).shape == (3, 5)
    back = np.asarray(dequantize_rows(q, s, np.float32))
    # symmetric per-row int8: error <= scale/2 per element
    err = np.abs(back - x)
    bound = np.asarray(s)[..., None] * 0.5 + 1e-7
    assert (err <= bound).all()


def test_codec_zero_rows_are_exact():
    from dynamo_tpu.ops.kv_quant import dequantize_rows, quantize_rows
    q, s = quantize_rows(np.zeros((2, 4, 16), np.float32))
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(dequantize_rows(q, s, np.float32)) == 0).all()


def test_page_bytes_halves_and_knob_validation():
    from dynamo_tpu.ops.kv_quant import page_bytes, validate_mode
    ref = page_bytes(16, 8, 64, 64, 2, False)   # llama3-1b geometry, bf16
    q = page_bytes(16, 8, 64, 64, 2, True)
    # int8 + f32 per-row scales: 2*64/(64+4) = 1.88x fewer bytes/page
    assert ref / q >= 1.8
    with pytest.raises(ValueError):
        validate_mode("int4")
    with pytest.raises(ValueError):
        NativeEngine(CFG, EngineConfig(kv_quant="fp8", **ENGINE_KW), seed=0)


# -- the committed parity gate -------------------------------------------------

def test_int8_parity_gate_cpu_fixture():
    """THE gate (acceptance bar): greedy-match rate >= KVQ_MATCH_MIN and
    prefill-logit drift within bound, via the same bench.run_kv_quant_
    parity implementation the TPU ladder runs — thresholds committed in
    bench.py, not re-derived here."""
    import bench
    verdict = bench.run_kv_quant_parity(
        CFG, engine_kwargs=ENGINE_KW, n_tokens=24, n_prompts=2,
        logf=lambda *a: None)
    assert verdict["pass"], verdict
    assert verdict["greedy_match_rate"] >= bench.KVQ_MATCH_MIN
    assert verdict["max_logit_drift"] <= verdict["drift_bound"]


# -- scheduler/pipeline invariance of the int8 engine --------------------------

def test_int8_identity_mixed_vs_alternating_and_pipelined(eng_q):
    """Mid-stream admissions, mixed + pipelined vs the alternating
    synchronous loop ON THE SAME int8 engine: token-identical. The
    representation must be invisible to scheduling (same pages, same
    scales, regardless of which step kind wrote them)."""
    from tests.test_mixed_steps import (
        PROMPTS, drive_alternating, drive_with_admissions,
    )
    greedy = [
        SamplingParams(max_tokens=10, temperature=0.0, ignore_eos=True),
        SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True),
        SamplingParams(max_tokens=5, temperature=0.0, ignore_eos=True)]
    m0 = eng_q.mixed_steps
    ref = drive_alternating(eng_q, "kq-ref", greedy, PROMPTS)
    mix = drive_with_admissions(eng_q, "kq-mix", greedy, PROMPTS)
    assert mix == ref
    assert eng_q.mixed_steps > m0          # fused steps really ran int8


def test_int8_seeded_sampled_identity(eng_q):
    """Seeded-sampled streams through the int8 engine: mixed/pipelined
    equals the alternating reference token-for-token (same (seed,
    counter) keys through sample_logits over int8-backed logits)."""
    from tests.test_mixed_steps import (
        PROMPTS, drive_alternating, drive_with_admissions,
    )
    sampled = [
        SamplingParams(max_tokens=8, temperature=0.9, top_k=12, seed=7,
                       ignore_eos=True),
        SamplingParams(max_tokens=6, temperature=0.7, top_p=0.8, seed=3,
                       ignore_eos=True),
        SamplingParams(max_tokens=5, temperature=0.8, seed=11,
                       ignore_eos=True)]
    ref = drive_alternating(eng_q, "kqs-ref", sampled, PROMPTS)
    mix = drive_with_admissions(eng_q, "kqs-mix", sampled, PROMPTS)
    assert mix == ref


# -- representation plumbing ---------------------------------------------------

def test_cache_layout_and_extract_inject_roundtrip(eng_q):
    """The cache dict carries int8 values + f32 per-row scales with the
    page axis shared; extract/inject move all four leaves by the same
    page ids (the whole-page contract every downstream hop relies on)."""
    import jax
    cache = eng_q.cache
    assert set(cache) == {"k", "v", "k_scale", "v_scale"}
    assert cache["k"].dtype == np.int8 and cache["v"].dtype == np.int8
    assert cache["k_scale"].dtype == np.float32
    assert cache["k"].shape[:4] == cache["k_scale"].shape
    # decode something so pages hold non-trivial bytes
    eng_q.generate(list(range(5, 29)),
                   SamplingParams(max_tokens=4, temperature=0.0,
                                  ignore_eos=True), "ex")
    pages = eng_q.extract_pages([0, 1])
    assert set(pages) == {"k", "v", "k_scale", "v_scale"}
    got = {key: np.asarray(jax.device_get(arr)) for key, arr in
           pages.items()}
    # inject them back at the same ids: cache unchanged at those pages
    eng_q.inject_pages([0, 1], pages["k"], pages["v"],
                       pages["k_scale"], pages["v_scale"])
    again = {key: np.asarray(jax.device_get(arr)) for key, arr in
             eng_q.extract_pages([0, 1]).items()}
    for key in got:
        np.testing.assert_array_equal(got[key], again[key])
    # a bf16-style inject without scales is a named config error
    with pytest.raises(ValueError, match="scales"):
        eng_q.inject_pages([0], pages["k"][:, :, :1], pages["v"][:, :, :1])


def test_metrics_carry_kv_repr_gauges(eng_q):
    from dynamo_tpu.ops.kv_quant import page_bytes
    m = eng_q.metrics()
    assert m.kv_quant_bits == 8
    mc, ec = eng_q.model_cfg, eng_q.cfg
    assert m.kv_page_bytes == page_bytes(
        mc.num_layers, mc.num_kv_heads, ec.page_size, mc.head_dim, 4, True)
    # wire path keeps the fields (the /metrics exporter's source)
    from dynamo_tpu.kv_router.scoring import WorkerMetrics
    w = WorkerMetrics.from_dict(dataclasses.asdict(m))
    assert w.kv_quant_bits == 8
    assert w.kv_page_bytes == m.kv_page_bytes


def test_int8_on_pp_mesh_identity_and_parity():
    """ISSUE 15 satellite (ROADMAP item 1b slice): kv_quant composes
    with pp — the GPipe stage scan threads the int8 scale-stack shards
    (models/pp._stage: write_kv_pages_quant at capture, dequant at the
    paged gather). Two bars in one engine set (tier-1 budget):

    - IDENTITY: the pp=2 int8 engine is token-identical to the
      single-device int8 engine, greedy AND seeded-sampled (same
      codec, different mesh — quantization changes values, never
      mesh-dependent behavior; the pp=2 x tp=2 interplay of
      vocab-sharded sampling with sharded caches is already pinned by
      test_pp's bf16 suite, and the tp scale-shard split by
      test_int8_on_tp_mesh_matches_single_device);
    - PARITY vs bf16-pp on the SAME mesh through the committed parity
      bar (bench.KVQ_MATCH_MIN greedy-match floor): quantization drift
      on a pp mesh is no worse than the single-mesh gate bounds."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from bench import KVQ_MATCH_MIN
    from dynamo_tpu.parallel.mesh import make_mesh
    kw = dict(page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=16,
              prefill_buckets=(8, 16), max_model_len=128, decode_steps=4)
    cfg = ModelConfig(dtype="float32", num_layers=4, max_model_len=128)
    greedy = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    sampled = SamplingParams(max_tokens=6, temperature=0.8, top_k=40,
                             top_p=0.95, seed=1234, ignore_eos=True)
    prompt = list(range(3, 15))
    prompt2 = list(range(40, 52))
    one = NativeEngine(cfg, EngineConfig(kv_quant="int8", **kw), seed=0)
    expect_g = one.generate(prompt, greedy, "og")
    expect_s = one.generate(prompt2, sampled, "os")
    mesh = make_mesh(pp=2, devices=jax.devices()[:2])
    q = NativeEngine(cfg, EngineConfig(kv_quant="int8", **kw), mesh=mesh,
                     seed=0)
    assert q.generate(prompt, greedy, "pg") == expect_g, \
        "greedy int8 pp=2 diverged from int8 single-device"
    assert q.generate(prompt2, sampled, "ps") == expect_s, \
        "sampled int8 pp=2 diverged from int8 single-device"
    # parity vs the unquantized pp twin (same mesh, same prompts)
    bf = NativeEngine(cfg, EngineConfig(**kw),
                      mesh=make_mesh(pp=2, devices=jax.devices()[:2]),
                      seed=0)
    p8 = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [[(7 * i + j) % 200 + 3 for j in range(12)]
               for i in range(3)]
    match = total = 0
    for i, pr in enumerate(prompts):
        a = bf.generate(pr, p8, f"b{i}")
        b = q.generate(pr, p8, f"q{i}")
        match += sum(1 for x, y in zip(a, b) if x == y)
        total += len(a)
    assert total > 0 and match / total >= KVQ_MATCH_MIN, \
        f"pp int8 greedy match {match}/{total} below {KVQ_MATCH_MIN}"


def test_int8_on_tp_mesh_matches_single_device():
    """tp=2 int8 engine (sharded scale stacks, shard_map'd dequant in
    the gather path) is token-identical to the single-device int8
    engine — the representation shards with the kv-head axis."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from dynamo_tpu.parallel.mesh import make_mesh
    kw = dict(page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=16,
              prefill_buckets=(8, 16), max_model_len=128, kv_quant="int8")
    cfg = ModelConfig(dtype="float32", num_layers=4, max_model_len=128)
    p = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    prompt = list(range(3, 15))
    one = NativeEngine(cfg, EngineConfig(**kw), seed=0)
    expect = one.generate(prompt, p, "o")
    mesh = make_mesh(tp=2, devices=jax.devices()[:2])
    eng = NativeEngine(cfg, EngineConfig(**kw), mesh=mesh, seed=0)
    assert eng.generate(prompt, p, "t") == expect
