"""Closed-loop fleet autoscaler (runtime/autoscaler.py, ISSUE 12).

Three layers, cheapest first:

- **decision units**: the do-no-harm machinery — cooldown, hysteresis,
  role-minimum and concurrent-drain guards, the degraded freeze, and
  the bounded-actuation window that keeps a wedged sensor from
  mass-draining the fleet — each driven with synthetic FleetSignals;
- **determinism**: the decision timeline is a pure function of the
  seeded signal sequence (two controllers, identical timelines), and
  the committed AUTOSCALE_r12.json storm replays bit-identically
  through the live simcluster path;
- **the tier-1 smoke**: a 64-worker simcluster diurnal + flash-crowd
  storm where the controller holds the TTFT SLO the static split
  burns through, with zero dropped streams and zero fence violations.

The `MixedBudgetTuner` (item-4 local self-tuning leg) is unit-tested
against a real bare Scheduler + StepLedger; the live-engine leg is the
AUTOSCALE_r12.json `budget_tuning` evidence (tools/fleet_storm.py).
"""
import asyncio
import json
import os

import pytest

from dynamo_tpu.observability.slo import SloSpec, SloWatchdog
from dynamo_tpu.observability.timeseries import SeriesStore
from dynamo_tpu.runtime.autoscaler import (
    ROLE_DECODE, ROLE_PREFILL, AutoscalerConfig, AutoscalerStats,
    FleetAutoscaler, FleetSignals, MixedBudgetTuner, RoleState,
    signals_from_store,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sig(ts, p_workers=8, d_workers=8, queue=0.0, p_occ=0.5, d_occ=0.5,
        ttft_burn=0.0, itl_burn=0.0, ttft_firing=False, itl_firing=False,
        degraded=False, drains=0, p_draining=0, d_draining=0):
    return FleetSignals(
        ts=ts,
        roles={ROLE_PREFILL: RoleState(workers=p_workers,
                                       draining=p_draining,
                                       queue_depth=queue,
                                       occupancy=p_occ),
               ROLE_DECODE: RoleState(workers=d_workers,
                                      draining=d_draining,
                                      occupancy=d_occ)},
        ttft_burn=ttft_burn, itl_burn=itl_burn,
        ttft_firing=ttft_firing, itl_firing=itl_firing,
        degraded=degraded, drains_active=drains)


def mk(**over):
    defaults = dict(min_prefill=2, min_decode=2, cooldown_s=10.0,
                    hysteresis_ticks=3, max_moves=2,
                    max_moves_per_window=8, window_s=60.0,
                    queue_hi=3.0, queue_lo=0.25, occ_hi=0.85,
                    occ_lo=0.30, burn_hi=1.0)
    defaults.update(over)
    stats = AutoscalerStats()
    return FleetAutoscaler(AutoscalerConfig(**defaults),
                           stats=stats), stats


CANDS = {ROLE_DECODE: [f"d{i}" for i in range(8)],
         ROLE_PREFILL: [f"p{i}" for i in range(8)]}


# -- decision units ------------------------------------------------------------

def test_hysteresis_then_decision_then_cooldown():
    asc, stats = mk()
    hot = dict(queue=40.0)      # 5 waiting per prefill worker: hot
    assert asc.decide(sig(0.0, **hot), CANDS) == []
    assert asc.decide(sig(1.0, **hot), CANDS) == []
    assert stats.hysteresis_suppressed == 2
    out = asc.decide(sig(2.0, **hot), CANDS)
    assert len(out) == 1
    d = out[0]
    assert d.kind == "re_role_to_prefill"
    assert d.from_role == ROLE_DECODE and d.to_role == ROLE_PREFILL
    # candidate order is preference order: least-loaded first
    assert d.workers == ("d0", "d1")
    assert stats.decisions_total == 1
    assert stats.decisions_re_role_to_prefill == 1
    # inside the cooldown the same sustained pressure is suppressed
    assert asc.decide(sig(3.0, **hot), CANDS) == []
    assert stats.cooldown_suppressed == 1
    # ... and fires again once the cooldown elapses
    assert asc.decide(sig(12.5, **hot), CANDS)[0].kind == \
        "re_role_to_prefill"


def test_one_tick_blip_never_actuates():
    asc, stats = mk()
    for t in range(10):
        blip = (t % 2 == 0)     # alternating pressure: direction resets
        out = asc.decide(sig(float(t), queue=40.0 if blip else 0.0),
                         CANDS)
        assert out == []
    assert stats.decisions_total == 0


def test_role_minimum_guard_refuses_to_drain_below_floor():
    asc, stats = mk(min_decode=8)    # decode already at its minimum
    for t in range(6):
        out = asc.decide(sig(float(t), queue=40.0), CANDS)
        assert out == []
    assert stats.guard_blocked > 0
    assert stats.decisions_total == 0


def test_concurrent_drain_guard():
    asc, stats = mk()
    for t in range(4):
        out = asc.decide(sig(float(t), queue=40.0, drains=1), CANDS)
        assert out == []
    assert stats.guard_blocked >= 1
    # the moment the drain finishes, the sustained pressure actuates
    assert asc.decide(sig(5.0, queue=40.0), CANDS)


def test_degraded_freeze_makes_zero_decisions():
    asc, stats = mk()
    # build a full streak, then degrade right at the firing tick
    asc.decide(sig(0.0, queue=40.0), CANDS)
    asc.decide(sig(1.0, queue=40.0), CANDS)
    for t in range(2, 8):
        assert asc.decide(sig(float(t), queue=40.0, degraded=True),
                          CANDS) == []
    assert stats.frozen_degraded == 6
    assert stats.decisions_total == 0
    # freeze HOLDS the streak (it neither grows nor resets): the first
    # healthy tick may act on the already-sustained pressure
    out = asc.decide(sig(8.0, queue=40.0), CANDS)
    assert len(out) == 1 and stats.frozen_degraded == 6


def test_bounded_actuation_caps_a_wedged_sensor():
    """A sensor pinned at 'bad' forever: total moved workers over any
    window stays at max_moves_per_window — the fleet is never
    mass-drained no matter how long the sensor lies."""
    asc, stats = mk(cooldown_s=1.0, hysteresis_ticks=1,
                    max_moves_per_window=4, window_s=1000.0,
                    min_decode=0)
    cands = {ROLE_DECODE: [f"d{i}" for i in range(50)],
             ROLE_PREFILL: []}
    moved = []
    for t in range(60):
        for d in asc.decide(sig(float(t), d_workers=50, queue=500.0),
                            cands):
            moved.extend(d.workers)
    assert len(moved) == 4            # the window bound, not 60 ticks' worth
    assert stats.guard_blocked > 0


def test_add_when_both_roles_hot_and_shed_when_idle():
    asc, _ = mk()
    for t in range(3):
        out = asc.decide(sig(float(t), queue=40.0, d_occ=0.95), CANDS)
    assert out[0].kind == "add" and out[0].count == 2
    assert out[0].to_role in (ROLE_PREFILL, ROLE_DECODE)
    asc2, _ = mk()
    for t in range(3):
        out = asc2.decide(sig(float(t), queue=0.0, p_occ=0.05,
                              d_occ=0.05), CANDS)
    assert out[0].kind == "shed" and out[0].count == 1


def test_empty_queue_with_busy_workers_is_not_idle():
    """Capacity exactly matching demand (empty queue, high occupancy)
    must not read as excess: no shed."""
    asc, stats = mk()
    for t in range(8):
        assert asc.decide(sig(float(t), queue=0.0, p_occ=0.7,
                              d_occ=0.6), CANDS) == []
    assert stats.decisions_total == 0


def test_homing_returns_the_split_to_target():
    asc, _ = mk(target_prefill_frac=0.5)
    for t in range(3):
        out = asc.decide(sig(float(t), p_workers=12, d_workers=4,
                             queue=0.0, p_occ=0.3, d_occ=0.5), CANDS)
    assert out[0].kind == "re_role_to_decode"
    assert "homing" in out[0].reason
    asc2, _ = mk(target_prefill_frac=0.5)
    for t in range(3):
        out = asc2.decide(sig(float(t), p_workers=4, d_workers=12,
                              queue=0.0, p_occ=0.5, d_occ=0.1), CANDS)
    assert out[0].kind == "re_role_to_prefill"
    assert "homing" in out[0].reason


def test_decision_timeline_is_deterministic():
    import random

    def timeline(seed):
        rng = random.Random(seed)
        asc, _ = mk(cooldown_s=3.0)
        for t in range(120):
            hot = 30.0 * (1 + rng.random()) if 40 <= t < 80 else 0.0
            occ = 0.4 + 0.2 * rng.random()
            asc.decide(sig(float(t), queue=hot, d_occ=occ), CANDS)
        return asc.timeline

    assert timeline(7) == timeline(7)
    assert len(timeline(7)) >= 1


def test_signals_from_store_reads_rollup_schema():
    store = SeriesStore(interval_s=1.0, capacity=64)
    ts = 100.0
    for field, v in (("workers", 6.0), ("draining", 1.0),
                     ("queue_depth", 12.0), ("occupancy", 0.8),
                     ("availability", 6 / 7)):
        store.record(f"role/prefill/{field}", v, ts)
    store.record("role/decode/workers", 10.0, ts)
    store.record("serving/ttft_p95", 5.0, ts)
    wd = SloWatchdog(store, [SloSpec(
        name="ttft_p95", series="serving/ttft_p95", objective=3.0,
        target=0.9, short_window_s=2.0, long_window_s=4.0,
        min_samples=1)], degraded_fn=lambda: False)
    wd.evaluate(ts)
    s = signals_from_store(store, wd, ts, drains_active=2)
    p = s.roles[ROLE_PREFILL]
    assert p.workers == 6 and p.draining == 1
    assert p.queue_depth == 12.0 and p.occupancy == 0.8
    assert s.roles[ROLE_DECODE].workers == 10
    assert s.ttft_burn == wd.states["ttft_p95"].burn_short
    assert s.drains_active == 2


# -- MixedBudgetTuner (ledger -> mixed_token_budget self-tuning) ---------------

def _bare_scheduler(sp=1):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.scheduler import Scheduler
    return Scheduler(EngineConfig(
        page_size=64, num_pages=32, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512, sp=sp))


def _ledger():
    from dynamo_tpu.observability.ledger import LedgerStats, StepLedger
    return StepLedger(enabled=True, stats=LedgerStats())


def _feed(led, useful, padded):
    led.record_step("mixed", 4, 2, useful, padded, 0, 32, 0, 0, 0, 0,
                    0, 0)


def test_budget_tuner_shrinks_on_padding_waste_bounded():
    sched = _bare_scheduler()
    led = _ledger()
    stats = AutoscalerStats()
    tuner = MixedBudgetTuner(sched, led, min_tokens=100, cooldown_s=2.0,
                             hysteresis_ticks=2, min_budget=128,
                             stats=stats)
    assert sched.mixed_token_budget == 512
    budgets = []
    ts = 0.0
    for _ in range(30):
        _feed(led, 100, 512)       # ~80% padding waste
        ts += 5.0
        out = tuner.tick(ts)
        if out is not None:
            budgets.append(out)
    # walked down in bounded multiplicative steps, clamped at the floor
    assert budgets and budgets[-1] == 128
    assert all(b >= 128 for b in budgets)
    assert sched.mixed_token_budget == 128
    assert stats.budget_adjustments == len(budgets)
    assert stats.budget_current == 128
    # floor reached: further waste makes no further adjustment
    before = stats.budget_adjustments
    _feed(led, 100, 512)
    assert tuner.tick(ts + 50.0) is None
    assert stats.budget_adjustments == before


def test_budget_tuner_grows_on_low_waste_and_needs_evidence():
    sched = _bare_scheduler()
    led = _ledger()
    tuner = MixedBudgetTuner(sched, led, min_tokens=100, cooldown_s=2.0,
                             hysteresis_ticks=2, max_budget=1024,
                             stats=AutoscalerStats())
    # below the evidence floor: no verdict at all
    _feed(led, 10, 20)
    assert tuner.tick(5.0) is None
    for i in range(6):
        _feed(led, 500, 512)       # ~2% waste: headroom
        tuner.tick(10.0 + 5 * i)
    assert sched.mixed_token_budget > 512
    assert sched.mixed_token_budget <= 1024


def test_budget_tuner_cooldown_and_hysteresis():
    sched = _bare_scheduler()
    led = _ledger()
    tuner = MixedBudgetTuner(sched, led, min_tokens=100,
                             cooldown_s=100.0, hysteresis_ticks=2,
                             stats=AutoscalerStats())
    _feed(led, 100, 512)
    assert tuner.tick(1.0) is None     # hysteresis: first waste window
    _feed(led, 100, 512)
    first = tuner.tick(2.0)            # second window: actuates
    assert first is not None
    _feed(led, 100, 512)
    _feed(led, 100, 512)
    assert tuner.tick(3.0) is None     # inside the cooldown
    assert sched.mixed_token_budget == first


def test_set_mixed_token_budget_clamps():
    sched = _bare_scheduler()
    floor = 2 * 8                      # smallest prefill bucket x 2
    assert sched.set_mixed_token_budget(4) == floor
    assert sched.set_mixed_token_budget(999) == 999
    assert sched.set_mixed_token_budget(0) == 0   # explicit mode flip
    sp = _bare_scheduler(sp=2)
    assert sp.set_mixed_token_budget(512) == 0    # sp stays alternating


# -- simcluster: the tier-1 smoke + committed-plan replay ----------------------

def _storm(workers, traffic, controller, ticks, degraded_window,
           seed=10):
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig

    async def main():
        sim = await SimCluster(SimConfig(
            workers=workers, streams=workers * 8, lease_ttl_s=30.0,
            seed=seed)).start()
        try:
            return await sim.autoscale_storm(
                traffic, ticks=ticks, controller=controller,
                degraded_window=tuple(degraded_window))
        finally:
            await sim.stop()

    return asyncio.run(main())


def test_autoscale_storm_controller_beats_static_64_workers():
    """The tier-1 smoke of the AUTOSCALE_r12 contract at 64 workers:
    the controller holds the TTFT SLO the static 32+32 split burns
    through, trades away no ITL, drops no streams across its re-role
    drains, freezes under the degraded window, and never violates the
    re-role fence."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_storm import TrafficShape
    traffic = TrafficShape(seed=21, base_rate=20.0)
    static = _storm(64, traffic, False, 300, (200, 220))
    ctrl = _storm(64, traffic, True, 300, (200, 220))
    assert static["slo"]["ttft_bad_ticks"] >= 10
    assert ctrl["slo"]["ttft_bad_ticks"] <= \
        static["slo"]["ttft_bad_ticks"] // 2
    assert ctrl["slo"]["itl_bad_ticks"] <= \
        static["slo"]["itl_bad_ticks"] + 2
    assert len(ctrl["controller"]["timeline"]) >= 2
    assert ctrl["streams"]["dropped"] == 0
    assert static["streams"]["dropped"] == 0
    assert ctrl["fence_violations"] == 0
    assert ctrl["decisions_in_degraded"] == 0
    assert ctrl["controller"]["frozen_degraded"] == 20


def test_autoscale_replay_matches_committed_artifact():
    """The committed AUTOSCALE_r12.json plan replays bit-identically:
    same traffic shape + seed through the live simcluster path yields
    the exact decision timeline (and the same SLO verdicts)."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_storm import TrafficShape
    path = os.path.join(REPO, "AUTOSCALE_r12.json")
    if not os.path.exists(path):
        pytest.skip("AUTOSCALE_r12.json not committed")
    with open(path) as f:
        plan = json.load(f)
    assert plan["ok"] is True
    traffic = TrafficShape.from_dict(plan["traffic"])
    replay = _storm(plan["workers"], traffic, True, plan["ticks"],
                    plan["degraded_window"], seed=plan["seed"])
    committed = plan["controller"]
    assert replay["controller"]["timeline"] == \
        committed["controller"]["timeline"]
    assert replay["slo"]["ttft_bad_ticks"] == \
        committed["slo"]["ttft_bad_ticks"]
    assert replay["streams"] == committed["streams"]
    assert replay["fence_violations"] == 0
