"""Real-checkpoint serving e2e (VERDICT r4 #4) as a regression test.

Runs tools/real_ckpt_e2e.py: builds a genuine HF checkpoint (trained
transformers LlamaForCausalLM + BPE tokenizer.json), serves it with the
one-command launcher over real HTTP, and requires the streamed greedy
completion to match transformers' generate() exactly.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_real_checkpoint_full_stack_matches_transformers(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "real_ckpt_e2e.py"),
         "--dir", str(tmp_path / "model"),
         "--out", str(tmp_path / "log.jsonl")],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert "PASS" in out.stdout
