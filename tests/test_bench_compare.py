"""Bench regression gate (tools/bench_compare.py + bench.trajectory_row).

Tier-1 runs the gate over the COMMITTED artifacts (BENCH_TRAJECTORY.jsonl
vs BASELINE.json gates) — a regression landing in the trajectory turns
the suite red — plus unit coverage of the skip/tolerance/exit-code
semantics on synthetic trajectories.
"""
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import bench_compare  # noqa: E402

TRAJ = os.path.join(REPO_ROOT, "BENCH_TRAJECTORY.jsonl")
BASE = os.path.join(REPO_ROOT, "BASELINE.json")


def _write(tmp_path, rows, gates=None):
    traj = tmp_path / "traj.jsonl"
    traj.write_text("".join(json.dumps(r) + "\n" for r in rows))
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"gates": gates or {}}))
    return str(traj), str(base)


def _row(value, run_id="r1", metric="m", extras=None):
    return {"run_id": run_id, "metric": metric, "value": value,
            "unit": "tok/s", "extras": extras or {}}


def test_committed_trajectory_passes_the_gate():
    """THE tier-1 gate: the committed trajectory vs BASELINE.json."""
    rc = bench_compare.main(["--trajectory", TRAJ, "--baseline", BASE,
                             "--quiet"])
    assert rc == 0
    report = bench_compare.compare(TRAJ, BASE)
    assert report["ok"]
    # the failed TPU-window captures (value 0 / extras.failure) were
    # skipped as non-measurements, not scored as regressions
    assert report["skipped_failed_captures"] >= 3
    assert report["results"][0]["source"] == "baseline"


def test_regression_beyond_tolerance_exits_nonzero(tmp_path):
    gates = {"m": {"baseline": 100.0, "rel_tolerance": 0.25}}
    traj, base = _write(tmp_path, [_row(70.0)], gates)
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 1


def test_tolerance_boundary_is_inclusive(tmp_path):
    gates = {"m": {"baseline": 100.0, "rel_tolerance": 0.25}}
    traj, base = _write(tmp_path, [_row(75.0)], gates)   # exactly the floor
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 0


def test_failed_capture_after_good_row_does_not_regress(tmp_path):
    gates = {"m": {"baseline": 100.0, "rel_tolerance": 0.25}}
    traj, base = _write(tmp_path, [
        _row(110.0, "good"),
        _row(0.0, "tunnel_down", extras={"failure": "no TPU"}),
    ], gates)
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 0
    report = bench_compare.compare(traj, base)
    assert report["results"][0]["run_id"] == "good"


def test_ungated_metric_trend_checks_against_previous_row(tmp_path):
    traj, base = _write(tmp_path, [_row(100.0, "a"), _row(60.0, "b")])
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 1
    traj2, base2 = _write(tmp_path, [_row(100.0, "a"), _row(90.0, "b")])
    assert bench_compare.main(["--trajectory", traj2, "--baseline", base2,
                               "--quiet"]) == 0


def test_no_measured_rows_is_exit_2(tmp_path):
    traj, base = _write(tmp_path, [_row(0.0)])
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 2


def test_lower_is_better_direction(tmp_path):
    gates = {"ttft": {"baseline": 0.1, "rel_tolerance": 0.5,
                      "direction": "lower"}}
    traj, base = _write(tmp_path, [_row(0.2, metric="ttft")], gates)
    assert bench_compare.main(["--trajectory", traj, "--baseline", base,
                               "--quiet"]) == 1
    traj2, base2 = _write(tmp_path, [_row(0.12, metric="ttft")], gates)
    assert bench_compare.main(["--trajectory", traj2, "--baseline", base2,
                               "--quiet"]) == 0


def test_trajectory_row_normalization():
    sys.path.insert(0, REPO_ROOT)
    from bench import trajectory_row
    row = trajectory_row(
        {"metric": "m", "value": 81.33, "unit": "tok/s",
         "vs_baseline": 0.08,
         "extras": {"failure": "x", "quant": "int8",
                    "tunnel_probes": ["dropped"], "huge": "dropped"}},
        run_id="r9")
    assert row["run_id"] == "r9"
    assert row["value"] == 81.33
    # bounded extras subset: fingerprint keys kept, blobs dropped
    assert set(row["extras"]) == {"failure", "quant"}


def test_gated_metric_with_no_measured_row_is_surfaced(tmp_path):
    gates = {"ghost": {"baseline": 10.0}}
    traj, base = _write(tmp_path, [_row(100.0, metric="m")], gates)
    report = bench_compare.compare(traj, base)
    skipped = [r for r in report["results"] if r["status"] == "skipped"]
    assert any(r["metric"] == "ghost" for r in skipped)
    assert report["ok"]   # surfaced, not failed (the tunnel owns it)
