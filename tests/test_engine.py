"""End-to-end engine tests: continuous batching, prefix cache, stop conditions."""
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams

CFG = ModelConfig(dtype="float32", max_model_len=512)


def make_engine(**kw):
    defaults = dict(
        page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512)
    defaults.update(kw)
    return NativeEngine(CFG, EngineConfig(**defaults), seed=0)


def test_greedy_generate_deterministic():
    eng1 = make_engine()
    eng2 = make_engine()
    prompt = list(range(10, 30))
    p = SamplingParams(max_tokens=8, temperature=0.0)
    out1 = eng1.generate(prompt, p, "a")
    out2 = eng2.generate(prompt, p, "b")
    assert len(out1) == 8
    assert out1 == out2


def test_chunked_prefill_same_output():
    """A prompt longer than max_prefill_chunk must give identical greedy
    output to an engine that prefills it in one chunk."""
    prompt = list(range(5, 53))  # 48 tokens
    p = SamplingParams(max_tokens=6, temperature=0.0)
    small = make_engine(max_prefill_chunk=16)
    big = make_engine(max_prefill_chunk=64, prefill_buckets=(8, 16, 32, 64))
    assert small.generate(prompt, p, "a") == big.generate(prompt, p, "b")


def test_continuous_batching_matches_sequential():
    """Concurrent greedy requests must produce the same tokens as running
    each alone (batching must not change results)."""
    prompts = [list(range(3, 19)), list(range(40, 50)), list(range(7, 36))]
    p = SamplingParams(max_tokens=5, temperature=0.0)
    solo = [make_engine().generate(pr, p, f"s{i}") for i, pr in enumerate(prompts)]

    eng = make_engine()
    for i, pr in enumerate(prompts):
        eng.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(len(prompts))}
    done = set()
    while len(done) < len(prompts):
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
    assert [got[f"r{i}"] for i in range(len(prompts))] == solo


def test_prefix_cache_reuse():
    eng = make_engine()
    prompt = list(range(1, 33))  # 32 tokens = 4 full pages
    p = SamplingParams(max_tokens=4, temperature=0.0)
    out1 = eng.generate(prompt, p, "a")
    m1 = eng.metrics()
    assert m1.gpu_prefix_cache_hit_rate == 0.0
    out2 = eng.generate(prompt, p, "b")
    assert out2 == out1
    m2 = eng.metrics()
    assert m2.gpu_prefix_cache_hit_rate > 0.0
    ev = eng.drain_kv_events()
    assert any(e[0] == "stored" for e in ev)


def test_seeded_sampling_deterministic():
    prompt = list(range(2, 20))
    p = SamplingParams(max_tokens=6, temperature=0.9, top_k=20, seed=1234)
    out1 = make_engine().generate(prompt, p, "a")
    out2 = make_engine().generate(prompt, p, "b")
    assert out1 == out2


def test_stop_token_hidden():
    """Engine must stop on a stop_token_id without emitting it."""
    eng = make_engine()
    prompt = list(range(10, 26))
    # first run to discover the greedy continuation
    ref = eng.generate(prompt, SamplingParams(max_tokens=6), "probe")
    stop = ref[2]
    eng2 = make_engine()
    out = eng2.generate(
        prompt, SamplingParams(max_tokens=6, stop_token_ids=(stop,)), "x")
    assert out == ref[:2]


def test_eos_and_max_tokens():
    eng = make_engine()
    prompt = list(range(10, 26))
    ref = eng.generate(prompt, SamplingParams(max_tokens=6), "probe")
    eos = ref[3]
    eng2 = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512),
        eos_token_ids={eos}, seed=0)
    out = eng2.generate(prompt, SamplingParams(max_tokens=6), "x")
    assert out == ref[:3]
    # ignore_eos overrides
    eng3 = NativeEngine(
        CFG, EngineConfig(page_size=8, num_pages=64, max_slots=4,
                          max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                          max_model_len=512),
        eos_token_ids={eos}, seed=0)
    out3 = eng3.generate(prompt, SamplingParams(max_tokens=6, ignore_eos=True), "y")
    assert out3 == ref


def test_decode_window_matches_single_step():
    """decode_steps=N must produce token-identical streams to decode_steps=1
    (the window only amortizes dispatch; sampling state — keys, counters,
    eos bans — advances identically on device). Covers stop-mid-window:
    max_tokens not divisible by the window discards trailing garbage."""
    prompt = list(range(11, 31))
    for p in (SamplingParams(max_tokens=7, temperature=0.0),
              SamplingParams(max_tokens=10, temperature=0.9, top_k=12,
                             seed=3, ignore_eos=True)):
        ref = make_engine(decode_steps=1).generate(prompt, p, "one")
        for n in (3, 4, 8):
            got = make_engine(decode_steps=n).generate(prompt, p, f"w{n}")
            assert got == ref, (n, got, ref)


def test_decode_window_concurrent_matches_sequential():
    """Multi-step windows with concurrent slots of different lengths must
    still match solo runs (per-slot max_pos gating, mid-window finishes)."""
    prompts = [list(range(3, 19)), list(range(40, 50)), list(range(7, 36))]
    ps = [SamplingParams(max_tokens=m, temperature=0.0) for m in (3, 9, 5)]
    solo = [make_engine(decode_steps=4).generate(pr, p, f"s{i}")
            for i, (pr, p) in enumerate(zip(prompts, ps))]
    eng = make_engine(decode_steps=4)
    for i, (pr, p) in enumerate(zip(prompts, ps)):
        eng.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(len(prompts))}
    done = set()
    while len(done) < len(prompts):
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
    assert [got[f"r{i}"] for i in range(len(prompts))] == solo


def test_batched_prefill_fewer_steps_same_tokens():
    """8 concurrent same-bucket prompts prefill in ONE device step (plus
    decode windows), vs 8 with batching off — and tokens are identical
    (VERDICT r2 weak #3: prefill must not serialize across arrivals)."""
    prompts = [list(range(7 * i + 1, 7 * i + 17)) for i in range(8)]
    p = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)

    def run(**kw):
        eng = make_engine(max_slots=8, **kw)
        for i, pr in enumerate(prompts):
            eng.add_request(EngineRequest(f"r{i}", pr, p))
        got = {f"r{i}": [] for i in range(8)}
        done = set()
        steps = 0
        while len(done) < 8:
            steps += 1
            for ev in eng.step():
                if ev.token is not None:
                    got[ev.request_id].append(ev.token)
                if ev.finished:
                    done.add(ev.request_id)
        return [got[f"r{i}"] for i in range(8)], steps

    batched, n_b = run(max_prefill_batch=8)
    serial, n_s = run(max_prefill_batch=1)
    assert batched == serial
    # serial: 8 prefill steps + decodes; batched: 1 prefill step + decodes
    assert n_s - n_b >= 7, (n_b, n_s)


def test_request_too_long_rejected():
    eng = make_engine()
    with pytest.raises(ValueError):
        eng.add_request(EngineRequest("big", list(range(600)), SamplingParams()))


def test_metrics_snapshot():
    eng = make_engine()
    eng.add_request(EngineRequest("m", list(range(20)), SamplingParams(max_tokens=4)))
    eng.step()
    m = eng.metrics()
    assert m.request_total_slots == 4
    assert m.kv_total_blocks == 64
    assert m.kv_active_blocks > 0


def test_prefill_streak_capped_decode_interleaves():
    """A long multi-chunk prefill must not starve running decodes: at most
    max_prefill_streak consecutive prefill steps, then a decode step runs
    (VERDICT r1 weak #3)."""
    from dynamo_tpu.engine.scheduler import (
        DecodePlan, PrefillPlan, Scheduler,
    )

    cfg = EngineConfig(page_size=8, num_pages=128, max_slots=2,
                       max_prefill_chunk=8, prefill_buckets=(8,),
                       max_model_len=512, max_prefill_streak=2,
                       mixed_token_budget=0)  # legacy alternating mode
    s = Scheduler(cfg)
    s.add_request(EngineRequest("a", list(range(2, 10)), SamplingParams(
        max_tokens=50, ignore_eos=True)))
    plan = s.schedule()
    assert isinstance(plan, PrefillPlan)
    s.commit_prefill(plan, 7)  # "a" now holds a decode slot
    # "b": 80 tokens -> 10 chunks of 8
    s.add_request(EngineRequest("b", list(range(100, 180)), SamplingParams(
        max_tokens=4, ignore_eos=True)))
    kinds = ""
    for _ in range(24):
        plan = s.schedule()
        if plan is None:
            break
        if isinstance(plan, PrefillPlan):
            kinds += "p"
            s.commit_prefill(plan, 9 if plan.is_last_chunk[0] else None)
        else:
            assert isinstance(plan, DecodePlan)
            kinds += "d"
            s.commit_decode(plan, np.zeros(cfg.max_slots, np.int64))
    # decode steps interleave: no prefill run longer than the streak limit
    runs = [len(r) for r in kinds.split("d") if r]
    assert runs and max(runs) <= 2, kinds
    assert kinds.count("p") == 10, kinds  # all chunks of "b" did run


def test_prefill_streak_unbounded_when_disabled():
    """max_prefill_streak=0 restores strict prefill-priority."""
    from dynamo_tpu.engine.scheduler import PrefillPlan, Scheduler

    cfg = EngineConfig(page_size=8, num_pages=128, max_slots=2,
                       max_prefill_chunk=8, prefill_buckets=(8,),
                       max_model_len=512, max_prefill_streak=0,
                       mixed_token_budget=0)  # legacy alternating mode
    s = Scheduler(cfg)
    s.add_request(EngineRequest("a", list(range(2, 10)), SamplingParams(
        max_tokens=50, ignore_eos=True)))
    s.commit_prefill(s.schedule(), 7)
    s.add_request(EngineRequest("b", list(range(100, 180)), SamplingParams(
        max_tokens=4, ignore_eos=True)))
    kinds = ""
    for _ in range(10):
        plan = s.schedule()
        if not isinstance(plan, PrefillPlan):
            kinds += "d"
            break
        kinds += "p"
        s.commit_prefill(plan, 9 if plan.is_last_chunk[0] else None)
    assert kinds == "p" * 10, kinds


def test_adaptive_window_is_ladder_rung_with_covering_pages():
    """The scheduler's adaptive decode window must (a) be a rung of the
    compiled ladder — any other value would miss the engine's program set
    and execute a LARGER window than pages were reserved for, scattering
    tail KV writes through zeroed page_table entries into page 0
    (code-review r3) — and (b) reserve pages covering the full rung for
    every slot, up to each request's own admission limit."""
    from dynamo_tpu.engine.scheduler import window_ladder

    eng = make_engine(decode_steps=64, max_slots=2)
    ladder = window_ladder(64)
    assert eng._window_sizes == ladder
    # one short-tail request (33 remaining) + one long one
    eng.add_request(EngineRequest(
        "short", list(range(10, 18)),
        SamplingParams(max_tokens=34, temperature=0.0, ignore_eos=True)))
    eng.add_request(EngineRequest(
        "long", list(range(40, 48)),
        SamplingParams(max_tokens=400, temperature=0.0, ignore_eos=True)))
    while eng.scheduler.waiting:
        eng.step()
    windows_seen = set()
    for _ in range(40):
        plan = eng.scheduler.schedule()
        if plan is None:
            break
        if not hasattr(plan, "n_window"):  # prefill plan
            eng._run_prefill(plan)
            continue
        assert plan.n_window in ladder, plan.n_window
        windows_seen.add(plan.n_window)
        for seq in plan.seqs:
            if seq is None:
                continue
            limit = (len(seq.prompt)
                     + eng.scheduler.params[seq.request_id].max_tokens)
            covered = len(seq.pages) * eng.cfg.page_size
            need = min(seq.total_len + plan.n_window, limit)
            assert covered >= need, (seq.request_id, covered, need)
        eng._run_decode(plan)
        if not any(s is not None for s in eng.scheduler.running):
            break
    # the short request's tail must have pulled the window below the max
    assert len(windows_seen) > 1, windows_seen


def test_stop_token_kills_window_writes_and_counts_waste():
    """VERDICT r3 weak #3: a hidden stop id sampled early in a multi-step
    decode window must stop the slot DEVICE-side — later window steps may
    not write KV for it — and the post-stop tail is surfaced via the
    wasted-step counters."""
    import jax.numpy as jnp

    prompt = list(range(10, 26))
    probe = make_engine(decode_steps=8)
    ref = probe.generate(prompt, SamplingParams(max_tokens=8,
                                                ignore_eos=True), "probe")
    stop = ref[1]  # first window-sampled token (ref[0] comes from prefill)
    if ref.count(stop) > 1:
        pytest.skip("greedy continuation repeats; pick a different seed")

    eng = make_engine(decode_steps=8)
    out = eng.generate(
        prompt,
        SamplingParams(max_tokens=8, ignore_eos=True,
                       stop_token_ids=(stop,)), "x")
    assert out == ref[:1]

    # KV beyond the stop position must be untouched zeros: positions
    # prompt..prompt+1 are written (fed token + the step that sampled the
    # stop); everything after may not be. Find the request's pages from
    # the probe run's layout (same scheduler decisions, same pages).
    ps = eng.cfg.page_size
    written_upto = len(prompt) + 2   # exclusive: pos 16 (fed), 17 (stop step)
    k = np.asarray(jnp.reshape(eng.cache["k"],
                               (CFG.num_layers, CFG.num_kv_heads, -1,
                                CFG.head_dim)))
    # pages 0/1 hold the prompt (16 toks), page 2 holds decode positions;
    # slot 0 was the only request so pages are 0,1,2 in order
    page = 2
    for pos in range(written_upto, len(prompt) + 8):
        flat = page * ps + (pos % ps)
        assert not np.any(k[:, :, flat]), (
            f"KV written at position {pos} after device-side stop")
    # the fed+stop positions ARE written (sanity that the window ran)
    assert np.any(k[:, :, page * ps + (len(prompt) % ps)])

    m = eng.metrics()
    # window of 8: the stop samples at window step 0 -> 7 wasted steps
    assert m.window_wasted_steps == 7
    assert m.window_slot_steps == 8
