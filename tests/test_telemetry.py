"""Resource-telemetry plane units (ISSUE 10 tentpole layers 1-3).

Everything here is deterministic and virtual-clocked: the TimeSeries
ring, the TransferCostModel EWMAs, the Histogram quantile estimator
(exactness at bucket boundaries and +Inf), the per-step ledger ring
discipline, and — the acceptance bar — the SLO burn-rate watchdog's
fire -> clear transition replayed from a seeded storm plan
(slo.seeded_storm_plan) with identical events on every run. The live
engine's ledger samples are covered in test_ledger_live_engine below
(one tiny engine, compile-cached); the live fleet rollup smoke is in
tests/test_fleet.py.
"""
import math

import pytest

from dynamo_tpu.observability.ledger import (
    LedgerStats, StepLedger, model_flops_per_token,
)
from dynamo_tpu.observability.metrics import Histogram
from dynamo_tpu.observability.slo import (
    SloSpec, SloWatchdog, seeded_storm_plan,
)
from dynamo_tpu.observability.timeseries import Ewma, SeriesStore, TimeSeries

# -- TimeSeries ----------------------------------------------------------------


def test_timeseries_bucketing_and_window():
    s = TimeSeries(interval_s=1.0, capacity=8)
    s.record(1.0, ts=10.2)
    s.record(2.0, ts=10.9)       # same bucket, reduce=last wins
    s.record(5.0, ts=12.5)       # gap at bucket 11
    assert s.latest() == 5.0
    assert s.window(3.0, ts=12.9) == [2.0, 5.0]   # gap absent, not zero
    assert s.avg(3.0, ts=12.9) == pytest.approx(3.5)
    assert s.max(3.0, ts=12.9) == 5.0


def test_timeseries_wraparound_hides_stale_buckets():
    s = TimeSeries(interval_s=1.0, capacity=4)
    for t in range(8):
        s.record(float(t), ts=float(t))
    # capacity 4: only buckets 4..7 survive; bucket 3's ring slot was
    # overwritten by bucket 7 and must not leak into a window read
    assert s.window(10.0, ts=7.5) == [4.0, 5.0, 6.0, 7.0]


def test_timeseries_reduce_modes_and_frac():
    mx = TimeSeries(interval_s=1.0, capacity=8, reduce="max")
    sm = TimeSeries(interval_s=1.0, capacity=8, reduce="sum")
    for v in (1.0, 3.0, 2.0):
        mx.record(v, ts=0.5)
        sm.record(v, ts=0.5)
    assert mx.latest() == 3.0
    assert sm.latest() == 6.0
    s = TimeSeries(interval_s=1.0, capacity=8)
    for t, v in ((0, 1.0), (1, 9.0), (2, 9.0), (3, 1.0)):
        s.record(v, ts=float(t))
    assert s.frac_where(lambda v: v > 5.0, 4.0, ts=3.5) == 0.5
    # below min_samples: no verdict, never "all good"
    assert s.frac_where(lambda v: v > 5.0, 4.0, ts=3.5,
                        min_samples=5) is None


def test_series_store_get_or_make_and_names():
    st = SeriesStore(interval_s=1.0, capacity=16)
    st.record("worker/w0/kv", 3.0, ts=1.0)
    st.record("fleet/live", 8.0, ts=1.0)
    assert st.names("worker/") == ["worker/w0/kv"]
    assert st.get("fleet/live").latest() == 8.0
    assert st.get("absent") is None
    assert len(st) == 2


def test_ewma_none_until_first_sample():
    e = Ewma(alpha=0.5)
    assert e.value is None
    e.update(10.0)
    e.update(20.0)
    assert e.value == pytest.approx(15.0)
    assert e.samples == 2


# -- TransferCostModel ---------------------------------------------------------


def test_transfer_cost_model_ewma_and_estimate():
    from dynamo_tpu.observability.fleet import TransferCostModel
    m = TransferCostModel(alpha=0.5, default_bytes_per_s=1e9)
    # unmeasured link: the default
    assert m.bandwidth_bytes_per_s("w9") == 1e9
    assert not m.measured("w9")
    m.observe("w0", nbytes=10_000_000, seconds=0.01)   # 1 GB/s
    m.observe("w0", nbytes=5_000_000, seconds=0.01)    # 0.5 GB/s
    assert m.measured("w0")
    assert m.bandwidth_bytes_per_s("w0") == pytest.approx(7.5e8)
    assert m.estimate_s("w0", 75_000_000) == pytest.approx(0.1)
    # degenerate samples are dropped, not divided by
    m.observe("w0", nbytes=0, seconds=1.0)
    m.observe("w0", nbytes=100, seconds=0.0)
    assert m.snapshot()["w0"]["samples"] == 2
    assert m.links() == ["w0"]


def test_transfer_cost_model_cold_start_fleet_median():
    """ISSUE 11 satellite pin: a never-measured link estimates at the
    fleet-median bandwidth with cold=True — neither free (zero cost)
    nor infinitely penalized."""
    from dynamo_tpu.observability.fleet import TransferCostModel
    m = TransferCostModel(default_bytes_per_s=1e9)
    # nothing measured anywhere: the default prior, still cold
    est = m.estimate("ghost", 1_000_000)
    assert est.cold and est.seconds == pytest.approx(1e-3)
    m.observe("slow", 1_000_000, 1.0)     # 1 MB/s
    m.observe("mid", 10_000_000, 1.0)     # 10 MB/s
    m.observe("fast", 100_000_000, 1.0)   # 100 MB/s
    assert m.fleet_median_bytes_per_s() == pytest.approx(1e7)
    est = m.estimate("ghost", 10_000_000)
    assert est.cold
    assert est.seconds == pytest.approx(1.0)      # finite, median-priced
    assert est.seconds > 0.0                      # never free
    assert not m.estimate("fast", 1).cold
    # estimate_s stays the scalar view of the same cold-aware answer
    assert m.estimate_s("ghost", 10_000_000) == pytest.approx(1.0)


def test_transfer_cost_model_backlog_and_estimator_error():
    from dynamo_tpu.observability.fleet import TransferCostModel
    m = TransferCostModel(alpha=0.5)
    m.observe("w0", 10_000_000, 1.0)      # believes 10 MB/s
    # estimator error records BEFORE each subsequent sample folds in:
    # a transfer at the believed speed -> ~0 error; a 2x-slower one ->
    # under-estimate (negative signed error)
    m.observe("w0", 10_000_000, 1.0)
    assert m.est_err_frac("w0") == pytest.approx(0.0, abs=1e-6)
    m.observe("w0", 10_000_000, 2.0)
    assert m.est_err_frac("w0") < 0.0
    assert m.mean_abs_est_err() > 0.0
    assert "est_err_frac" in m.snapshot()["w0"]
    # in-flight backlog: queue_s prices the unfinished bytes at the
    # link's bandwidth and drains back to zero on completion
    m.note_inflight("w0", 5_000_000)
    assert m.backlog_bytes("w0") == 5_000_000
    assert m.queue_s("w0") > 0.0
    m.note_done("w0", 5_000_000)
    assert m.backlog_bytes("w0") == 0
    assert m.queue_s("w0") == 0.0


# -- Histogram.quantile --------------------------------------------------------


def test_quantile_boundary_exactness_and_interpolation():
    h = Histogram("q", "h", buckets=(1.0, 2.0, 4.0, float("inf")))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(value=v)
    # rank lands EXACTLY on bucket 1's cumulative count (1 of 4) ->
    # that bucket's upper bound, exactly
    assert h.quantile(0.25) == 1.0
    # rank 3 of 4 lands exactly on bucket 2's cumulative -> 2.0
    assert h.quantile(0.75) == 2.0
    # interpolation inside bucket (1, 2]: rank 2 of 4, one of two
    # samples into the bucket -> midpoint
    assert h.quantile(0.5) == pytest.approx(1.5)
    assert h.quantile(1.0) == 4.0


def test_quantile_inf_bucket_reports_largest_finite_bound():
    h = Histogram("q2", "h", buckets=(1.0, float("inf")))
    h.observe(value=50.0)
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == 1.0


def test_quantile_empty_and_labels_and_all():
    h = Histogram("q3", "h", ("model",), buckets=(1.0, 2.0, float("inf")))
    assert math.isnan(h.quantile(0.5, "m"))
    h.observe("a", value=0.5)
    h.observe("b", value=1.5)
    assert h.quantile(0.5, "a") == pytest.approx(0.5)
    assert h.quantile(0.5, "b") == pytest.approx(1.5)
    # aggregate across label sets: 2 samples, p100 in bucket (1, 2]
    assert h.quantile_all(1.0) == 2.0
    with pytest.raises(ValueError):
        h.quantile(0.0, "a")


# -- StepLedger ----------------------------------------------------------------


def _sample(ledger, kind="decode", useful=4, padded=16, recomp=0):
    ledger.record_step(kind, rows=4, rows_live=2, useful=useful,
                       padded=padded, kv_used=3, kv_total=32,
                       host_used=0, host_total=0, disk_used=0,
                       disk_total=0, waiting=1, recompiles=recomp)


def test_ledger_ring_bounds_and_drain_order():
    st = LedgerStats()
    led = StepLedger(capacity=4, enabled=True, stats=st)
    for i in range(6):
        _sample(led, useful=i)
    assert len(led) == 4
    assert led.dropped == 2
    recs = led.drain()
    assert [r["tokens_useful"] for r in recs] == [2, 3, 4, 5]  # oldest first
    assert len(led) == 0               # drain clears
    assert st.steps_total == 6
    assert st.samples_dropped == 2


def test_ledger_disabled_is_branch_only():
    st = LedgerStats()
    led = StepLedger(capacity=8, enabled=False, stats=st)
    _sample(led)
    assert len(led) == 0
    assert led.steps == 0
    assert st.steps_total == 0


def test_ledger_per_kind_padding_attribution_and_pad_fraction():
    st = LedgerStats()
    led = StepLedger(capacity=32, enabled=True, stats=st)
    _sample(led, kind="prefill", useful=10, padded=16)
    _sample(led, kind="mixed", useful=6, padded=32)
    _sample(led, kind="decode", useful=4, padded=16, recomp=2)
    assert st.useful_tokens_prefill == 10
    assert st.padded_tokens_mixed == 32
    assert st.recompiles == 2
    assert led.pad_fraction() == pytest.approx(1.0 - 20 / 64)
    s = led.summary()
    assert s["steps_by_kind"] == {"prefill": 1, "mixed": 1, "decode": 1}
    assert s["recompiles"] == 2


def test_ledger_mfu_needs_peak_and_flops():
    from dynamo_tpu.engine.config import ModelConfig
    cfg = ModelConfig()
    fpt = model_flops_per_token(cfg)
    assert fpt > 0
    led = StepLedger(capacity=8, enabled=True, stats=LedgerStats(),
                     flops_per_token=fpt)
    assert led.mfu == 0.0               # no peak configured
    led.configure(peak_tflops=1.0)
    led._tok_s = 1000.0
    assert led.mfu == pytest.approx(1000.0 * fpt / 1e12)


def test_ledger_jsonl_write_policy(tmp_path):
    led = StepLedger(capacity=8, enabled=True, stats=LedgerStats())
    _sample(led)
    _sample(led)
    path = str(tmp_path / "LEDGER_test.jsonl")
    assert led.write_jsonl(path) == 2
    import json
    rows = [json.loads(line) for line in open(path)]
    assert rows[0]["kind"] == "decode"
    assert set(rows[0]) >= {"ts", "dt", "kind", "tokens_useful",
                            "tokens_padded", "kv_used", "recompiles",
                            "tok_s", "mfu"}


# -- SLO watchdog --------------------------------------------------------------


def _run_plan(seed, spec_kw=None, degraded_fn=None):
    store = SeriesStore(interval_s=1.0, capacity=600)
    for ts, v in seeded_storm_plan(seed, n_intervals=120, storm_start=40,
                                   storm_len=40, good_value=0.05,
                                   bad_value=2.0):
        store.record("serving/ttft_p95", v, ts)
    kw = dict(name="ttft_p95", series="serving/ttft_p95", objective=0.5,
              target=0.9, short_window_s=10, long_window_s=30,
              burn_threshold=2.0)
    kw.update(spec_kw or {})
    wd = SloWatchdog(store, [SloSpec(**kw)],
                     degraded_fn=degraded_fn or (lambda: False))
    events = []
    for t in range(120):
        events.extend(wd.evaluate(float(t)))
    return wd, events


def test_slo_fire_clear_transition_is_deterministic_from_seeded_plan():
    """THE acceptance smoke: the seeded plan produces exactly one fire
    during the storm and one clear after recovery, at identical
    timestamps on every run (same seed => same events)."""
    runs = [_run_plan(7) for _ in range(2)]
    for wd, events in runs:
        kinds = [e["event"] for e in events]
        assert kinds == ["fire", "clear"]
        fire, clear = events
        assert 40 <= fire["ts"] < 80          # inside the storm window
        assert clear["ts"] > 80               # after recovery
        assert not wd.firing()
        assert wd.states["ttft_p95"].transitions == 2
    assert runs[0][1] == runs[1][1]           # bit-identical timelines


def test_slo_short_spike_alone_does_not_fire():
    """Multi-window: a burst shorter than the long window's threshold
    share never pages (the blip-protection half of the method)."""
    store = SeriesStore(interval_s=1.0, capacity=600)
    for t in range(120):
        bad = 50 <= t < 54                    # 4s spike
        store.record("s", 2.0 if bad else 0.05, float(t))
    wd = SloWatchdog(store, [SloSpec(
        name="x", series="s", objective=0.5, target=0.9,
        short_window_s=4, long_window_s=60, burn_threshold=2.0)],
        degraded_fn=lambda: False)
    events = []
    for t in range(120):
        events.extend(wd.evaluate(float(t)))
    assert events == []
    # the short window DID burn hot at the spike — the long window held
    assert wd.states["x"].transitions == 0


def test_slo_missing_data_yields_no_verdict():
    store = SeriesStore(interval_s=1.0, capacity=600)
    wd = SloWatchdog(store, [SloSpec(
        name="x", series="s", objective=0.5, target=0.9,
        short_window_s=5, long_window_s=10, min_samples=3)],
        degraded_fn=lambda: False)
    assert wd.evaluate(10.0) == []
    st = wd.states["x"]
    assert st.burn_short is None and st.burn_long is None
    assert not st.firing


def test_slo_degraded_exempt_freezes_state():
    """A degraded_exempt spec must not fire during the storm while the
    sanctioned degraded mode is up — and counts the suppressions."""
    degraded = {"on": False}
    store = SeriesStore(interval_s=1.0, capacity=600)
    for ts, v in seeded_storm_plan(3, storm_start=40, storm_len=40,
                                   good_value=0.05, bad_value=2.0):
        store.record("s", v, ts)
    wd = SloWatchdog(store, [SloSpec(
        name="lag", series="s", objective=0.5, target=0.9,
        short_window_s=10, long_window_s=30, burn_threshold=2.0,
        degraded_exempt=True)], degraded_fn=lambda: degraded["on"])
    events = []
    for t in range(120):
        degraded["on"] = 35 <= t < 95   # degraded covers the burn span
        events.extend(wd.evaluate(float(t)))
    assert events == []                 # never fired despite the burn
    assert wd.states["lag"].suppressed > 0


def test_slo_below_mode_and_gauges_render():
    store = SeriesStore(interval_s=1.0, capacity=600)
    for t in range(40):
        store.record("bw", 2e7 if t >= 20 else 1e9, float(t))
    wd = SloWatchdog(store, [SloSpec(
        name="bw_floor", series="bw", objective=1e8, mode="below",
        target=0.9, short_window_s=5, long_window_s=15,
        burn_threshold=2.0)], degraded_fn=lambda: False)
    for t in range(40):
        wd.evaluate(float(t))
    assert wd.firing() == ["bw_floor"]
    body = wd.render()
    assert 'llm_slo_firing{slo="bw_floor"} 1' in body
    assert "# HELP llm_slo_burn_rate_short" in body


def test_slo_alert_event_shape_and_on_alert():
    seen = []
    wd, events = _run_plan(11)
    wd2, _ = _run_plan(11)
    ev = events[0]
    assert set(ev) >= {"event", "slo", "ts", "series", "objective",
                       "burn_short", "burn_long", "threshold"}
    # on_alert callback receives each event as it happens
    store = SeriesStore(interval_s=1.0, capacity=600)
    for ts, v in seeded_storm_plan(11):
        store.record("serving/ttft_p95", v, ts)
    wd3 = SloWatchdog(store, [SloSpec(
        name="ttft_p95", series="serving/ttft_p95", objective=0.5,
        target=0.9, short_window_s=10, long_window_s=30)],
        on_alert=seen.append, degraded_fn=lambda: False)
    for t in range(120):
        wd3.evaluate(float(t))
    assert [e["event"] for e in seen] == ["fire", "clear"]


def test_slo_duplicate_names_rejected():
    store = SeriesStore()
    spec = SloSpec(name="a", series="s", objective=1.0)
    with pytest.raises(ValueError):
        SloWatchdog(store, [spec, SloSpec(name="a", series="t",
                                          objective=2.0)])


# -- prometheus text parsing + fleet_top rendering ----------------------------


def test_parse_prometheus_text_families_and_histograms():
    from dynamo_tpu.observability.fleet import parse_prometheus_text
    text = "\n".join([
        "# HELP llm_workers Live worker instances",
        "# TYPE llm_workers gauge",
        "llm_workers 3",
        "# HELP llm_ttft_seconds ttft",
        "# TYPE llm_ttft_seconds histogram",
        'llm_ttft_seconds_bucket{model="m",le="+Inf"} 2',
        'llm_ttft_seconds_sum{model="m"} 0.5',
        'llm_ttft_seconds_count{model="m"} 2',
        "# HELP llm_empty_family no series yet",
        "# TYPE llm_empty_family gauge",
    ])
    fams = parse_prometheus_text(text)
    assert fams["llm_workers"][""] == 3.0
    assert "llm_empty_family" in fams          # presence without series
    assert 'llm_ttft_seconds' in fams          # suffixes rolled up
    assert all(not k.endswith(("_bucket", "_sum", "_count"))
               for k in fams)


def test_fleet_top_renders_committed_artifact():
    """The committed FLEET_r10.json renders offline: the storm phase
    shows the burn, the timeline shows fire then clear, and every
    contract reads PASS (golden over the committed evidence)."""
    import json
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "FLEET_r10.json")
    import sys
    sys.path.insert(0, os.path.join(root, "tools"))
    from fleet_top import render_artifact, render_summary
    report = json.load(open(path))
    out = render_artifact(report)
    assert "fleet_availability" in out
    assert " fire " in out and " clear " in out
    assert "FAIL" not in out and "PASS" in out
    # the storm-phase rollup alone renders through render_summary
    storm = render_summary(report["rollup"]["storm"],
                           slo=report["slo_states"]["storm"])
    assert "FIRING" in storm
    assert "kv-transfer links" in storm


def test_trace_explain_summary_uses_bucket_quantiles():
    """tools/trace_explain.py --summary over the committed disagg trace:
    per-span-name p50/p95/p99 through Histogram.quantile (the estimator
    satellite's second consumer)."""
    import os
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "tools"))
    from trace_explain import load_spans, summarize
    spans = load_spans(os.path.join(root, "TRACE_DISAGG_r08.jsonl"))
    out = summarize(spans)
    assert "p95 ms" in out and "http.request" in out
    assert "kv.transfer" in out
    assert "decode.emit" in out and "instant" in out
    # ordered by total time: the root request dominates
    lines = [ln for ln in out.splitlines() if "http.request" in ln
             or "kv.transfer " in ln]
    assert lines[0].strip().startswith("http.request")
    # the pre-ISSUE-11 artifact carries no est_s attrs: the estimator
    # table must NOT appear (old goldens render unchanged)
    assert "estimator" not in out


def test_trace_explain_link_estimator_table():
    """ISSUE 11 satellite: kv.transfer spans carrying the sender's
    pre-send est_s attr render a per-link estimated-vs-actual column —
    a stale-fast EWMA (under-estimate) shows as negative err%."""
    import os
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "tools"))
    from trace_explain import link_estimator_table, summarize

    def span(link, est, dur, cold=False):
        return {"trace_id": "t", "span_id": link + str(est), "ts": 0.0,
                "dur": dur, "name": "kv.transfer",
                "attrs": {"engine_id": link, "est_s": est,
                          "bytes": 1000, "est_cold": cold}}

    spans = [span("fast", 0.010, 0.010),
             span("stale", 0.010, 0.100),     # 10x under-estimated
             span("coldlink", 0.020, 0.030, cold=True)]
    table = "\n".join(link_estimator_table(spans))
    assert "stale" in table and "fast" in table
    stale_row = next(ln for ln in table.splitlines() if "stale" in ln)
    assert "-90.0" in stale_row          # (est - act)/act = -90%
    cold_row = next(ln for ln in table.splitlines() if "coldlink" in ln)
    assert cold_row.rstrip().endswith("1")   # cold estimate counted
    # the table folds into --summary output
    assert "estimator" in summarize(spans)


def test_fleet_r10_artifact_contracts():
    """The committed evidence itself: fire -> clear present, per-link
    EWMAs measured, ledger samples from a live engine attached."""
    import json
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = json.load(open(os.path.join(root, "FLEET_r10.json")))
    assert report["ok"] is True
    assert all(report["contracts"].values())
    kinds = [(e["event"], e["slo"]) for e in report["alerts"]]
    assert ("fire", "fleet_availability") in kinds
    assert ("clear", "fleet_availability") in kinds
    assert len(report["rollup"]["storm"]["links"]) >= 8
    led = report["ledger"]
    assert led["samples"] > 0 and led["written"] == led["samples"]
    ledger_path = os.path.join(root, "LEDGER_r10.jsonl")
    rows = [json.loads(line) for line in open(ledger_path)]
    assert len(rows) == led["written"]
    assert {r["kind"] for r in rows} >= {"prefill", "decode"}
