"""Composable pipeline node graph + SDK dynamic .link() (VERDICT r3 #7).

Reference analogues: lib/runtime/src/pipeline/nodes.rs:72-209 (typed
Source/Operator/Sink chains) and the SDK's dynamic graph composition
(deploy/dynamo/sdk/src/dynamo/sdk/lib/service.py:173).
"""
import asyncio

import pytest

from dynamo_tpu.runtime.pipeline import (
    FnOperator, FnSink, Operator, Segment, source,
)


def run(coro):
    return asyncio.run(coro)


async def collect(it):
    return [x async for x in it]


async def echo_engine(request, context):
    for i in range(request["n"]):
        yield {"i": i, "via": request.get("via", [])}


class Doubler(Operator):
    """Request-transforming operator: doubles n, stamps itself."""

    async def generate(self, request, context, downstream):
        request = {**request, "n": request["n"] * 2,
                   "via": request.get("via", []) + ["doubler"]}
        async for frame in downstream.generate(request, context):
            yield frame


class Suffixer(Operator):
    """Response-transforming operator: appends a trailer frame."""

    async def generate(self, request, context, downstream):
        async for frame in downstream.generate(request, context):
            yield frame
        yield {"trailer": True}


def test_chain_composition_and_order():
    seg = source(Doubler(), Suffixer()).link(echo_engine)
    out = run(collect(seg.generate({"n": 2}, None)))
    # doubler ran before the sink (n=4), suffixer appended after
    assert [f.get("i") for f in out[:-1]] == [0, 1, 2, 3]
    assert all(f["via"] == ["doubler"] for f in out[:-1])
    assert out[-1] == {"trailer": True}


def test_segments_nest_as_sinks():
    inner = source(Suffixer()).link(echo_engine)
    outer = source(Doubler()).link(inner)
    out = run(collect(outer.generate({"n": 1}, None)))
    assert [f.get("i") for f in out[:-1]] == [0, 1]
    assert out[-1] == {"trailer": True}


def test_dynamic_sink_rewiring():
    seg = source().link(echo_engine)
    assert len(run(collect(seg.generate({"n": 3}, None)))) == 3

    async def other_engine(request, context):
        yield {"other": True}

    seg.set_sink(other_engine)  # discovery hot-swap
    assert run(collect(seg.generate({"n": 3}, None))) == [{"other": True}]


def test_operator_replacement_and_errors():
    seg = Segment()
    with pytest.raises(RuntimeError, match="no sink"):
        run(collect(seg.generate({}, None)))
    with pytest.raises(TypeError):
        seg.link(42)
    seg.link(FnOperator(Doubler().generate)).link(FnSink(echo_engine))
    with pytest.raises(ValueError, match="already has a sink"):
        seg.link(echo_engine)
    seg.set_operator(0, Suffixer())
    out = run(collect(seg.generate({"n": 1}, None)))
    assert out[-1] == {"trailer": True} and len(out) == 2


def test_local_pipeline_segment_hot_swap():
    """The OpenAI pipeline's token flow rides the graph: swapping the
    sink swaps the engine under a live model without rebuilding the
    preprocessor."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import LocalPipeline
    from dynamo_tpu.runtime.engine import Context

    card = ModelDeploymentCard(name="m", arch="tiny", tokenizer_kind="byte",
                               context_length=512, eos_token_ids=[2])

    class TokenEngine:
        def __init__(self, tok):
            self.tok = tok

        async def generate(self, request, context):
            yield {"token_ids": [self.tok], "finish_reason": "stop"}

    pipe = LocalPipeline(card, TokenEngine(65))
    pre, _ = pipe.preprocessor.preprocess_completion(
        __import__("dynamo_tpu.protocols.openai", fromlist=["x"])
        .CompletionRequest(model="m", prompt="hi"), "r1")
    out1 = run(collect(pipe._token_stream(pre, Context("r1"))))
    assert out1[0]["token_ids"] == [65]
    pipe.segment.set_sink(
        __import__("dynamo_tpu.llm.pipeline", fromlist=["x"])
        .LocalEngineSink(TokenEngine(66)).generate)
    out2 = run(collect(pipe._token_stream(pre, Context("r2"))))
    assert out2[0]["token_ids"] == [66]


def test_sdk_dynamic_link_unlink():
    from dynamo_tpu.sdk import service
    from dynamo_tpu.sdk.service import collect_graph

    @service(name="LinkFront", namespace="t")
    class LinkFront:
        pass

    @service(name="LinkMid", namespace="t")
    class LinkMid:
        pass

    @service(name="LinkLeaf", namespace="t")
    class LinkLeaf:
        pass

    # left-to-right chaining along the request path (reference .link())
    assert LinkFront.link(LinkMid).link(LinkLeaf) is LinkLeaf
    order = [s.name for s in collect_graph(LinkFront)]
    assert order == ["LinkLeaf", "LinkMid", "LinkFront"]  # deps first
    assert LinkFront.__service_spec__.dependencies["link_mid"] is LinkMid

    # conflicting re-link rejected; unlink then relink allowed
    @service(name="LinkMid2", namespace="t")
    class LinkMid2:
        pass

    with pytest.raises(ValueError, match="already depends"):
        LinkFront.link(LinkMid2, attr="link_mid")
    LinkFront.unlink(LinkMid)
    LinkFront.link(LinkMid2, attr="link_mid")
    assert LinkFront.__service_spec__.dependencies["link_mid"] is LinkMid2

    with pytest.raises(TypeError, match="not a @service"):
        LinkFront.link(object)
