"""Sequence-parallel (ring attention) prefill through the full engine."""
import jax
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.parallel.mesh import make_mesh

CFG = ModelConfig(dtype="float32", max_model_len=256)
PARAMS = SamplingParams(max_tokens=5, temperature=0.0, ignore_eos=True)


def _cfg(sp):
    return EngineConfig(
        page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=256,
        prefill_buckets=(8, 16, 32, 64, 128, 256), max_model_len=256, sp=sp)


def test_sp_prefill_matches_single_device():
    prompt = list(range(3, 83))  # 80 tokens -> bucket 128, divisible by sp
    expect = NativeEngine(CFG, _cfg(sp=1), seed=0).generate(
        prompt, PARAMS, "ref")
    mesh = make_mesh(sp=4, devices=jax.devices()[:4])
    eng = NativeEngine(CFG, _cfg(sp=4), mesh=mesh, seed=0)
    got = eng.generate(prompt, PARAMS, "sp")
    assert got == expect


def test_sp_with_tp_mesh():
    prompt = list(range(40, 100))
    mesh1 = make_mesh(tp=2, devices=jax.devices()[:2])
    expect = NativeEngine(CFG, _cfg(sp=1), mesh=mesh1, seed=0).generate(
        prompt, PARAMS, "ref")
    mesh = make_mesh(sp=4, tp=2)
    eng = NativeEngine(CFG, _cfg(sp=4), mesh=mesh, seed=0)
    got = eng.generate(prompt, PARAMS, "sptp")
    assert got == expect


def test_sp_requires_whole_prompt_prefill():
    with pytest.raises(ValueError, match="whole-prompt"):
        NativeEngine(CFG, EngineConfig(
            page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=32,
            prefill_buckets=(8, 16, 32), max_model_len=256, sp=4),
            mesh=make_mesh(sp=4, devices=jax.devices()[:4]))
