"""Tiered-KV streaming decode (engine/streaming.py): contexts beyond HBM.

The headline invariant: a decode whose context is 4x the HBM page budget —
cold KV pages streamed through the host tier into the pinned window pool,
double-buffered prefetch overlapped with compute — must be token-for-token
IDENTICAL to an engine with an oversized budget, greedy and seeded-sampled
alike. Streaming moves bytes, never semantics: K rows are stored post-RoPE
so placement is attention-neutral, and the partial-softmax combine across
resident + streamed segments is the exact flash merge.

Also under test: verify-on-fetch (a rotted cold page quarantines and ONLY
the victim page is recomputed from its token span), preempt/resume with a
partially-streamed window (silent KV replay, no duplicate emissions),
export/import migration records, int8 kv_quant scale leaves riding the
window pool, and the attention-mass EWMA spill policy.
"""
import json

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.engine.streaming import STREAM_STATS, StreamPolicy
from dynamo_tpu.runtime.faults import FaultSchedule, FaultSpec, REGISTRY

PAGE = 4
# 80 prompt + 16 output = 24 context pages vs a 6-page HBM budget (4x)
PROMPT = [(7 * i + 3) % 250 + 1 for i in range(80)]
GREEDY = SamplingParams(max_tokens=16, temperature=0.0, ignore_eos=True)
SAMPLED = SamplingParams(max_tokens=16, temperature=0.8, top_k=20,
                         top_p=0.9, seed=1234, ignore_eos=True)


def oracle_engine(kv_quant=""):
    """Oversized HBM budget: every page stays resident, nothing streams."""
    return NativeEngine(
        ModelConfig(dtype="float32", max_model_len=256, kv_quant=kv_quant),
        EngineConfig(page_size=PAGE, num_pages=64, max_slots=2,
                     max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                     max_model_len=256, kv_quant=kv_quant), seed=0)


def stream_engine(kv_quant="", **kw):
    cfg = dict(page_size=PAGE, num_pages=6, max_slots=2,
               max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
               max_model_len=256, host_pages=64, stream_pages=4,
               stream_resident_pages=4, stream_hot_pages=2,
               kv_quant=kv_quant)
    cfg.update(kw)
    return NativeEngine(
        ModelConfig(dtype="float32", max_model_len=256, kv_quant=kv_quant),
        EngineConfig(**cfg), seed=0)


def drive(eng, out):
    """One engine step, collecting emitted tokens into `out`."""
    for ev in eng.step():
        if ev.token is not None:
            out.append(ev.token)


@pytest.fixture(autouse=True)
def _clean_faults():
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    yield
    REGISTRY.disarm()
    REGISTRY.reset_counters()


# -- oracle identity -----------------------------------------------------------

def test_stream_greedy_matches_oracle():
    expect = oracle_engine().generate(PROMPT, GREEDY, "a")
    s0 = STREAM_STATS.snapshot()
    got = stream_engine().generate(PROMPT, GREEDY, "a")
    s1 = STREAM_STATS.snapshot()
    assert got == expect
    # the run must actually have streamed: spills happened, the double
    # buffer prefetched, and hits dominated lates (on CPU the synchronous
    # host tier never turns a prefetch late; the assert is one-sided to
    # stay robust on slower tiers)
    assert s1["pages_spilled"] > s0["pages_spilled"]
    assert s1["prefetch_issued"] > s0["prefetch_issued"]
    hits = s1["prefetch_hit"] - s0["prefetch_hit"]
    lates = s1["prefetch_late"] - s0["prefetch_late"]
    assert hits > lates


def test_stream_sampled_matches_oracle():
    """Seeded sampling: the streamer reuses the decode window's sampler
    tail with the same (seed, counter) keys, so stochastic streams are
    oracle-exact too, not just argmax."""
    expect = oracle_engine().generate(PROMPT, SAMPLED, "a")
    got = stream_engine().generate(PROMPT, SAMPLED, "a")
    assert got == expect


def test_stream_int8_kv_quant_identity_and_scale_leaves():
    """int8 cold pages stream verbatim — quantized rows + scale leaves
    staged into the window pool, dequantized only at attention consume —
    and the tokens still match the int8 oracle exactly."""
    expect = oracle_engine(kv_quant="int8").generate(PROMPT, GREEDY, "a")
    eng = stream_engine(kv_quant="int8")
    got = eng.generate(PROMPT, GREEDY, "a")
    assert got == expect
    pool = eng._streamer.pool
    assert pool._quant
    staged = [h for h in pool._half if h is not None]
    assert staged, "window pool never staged a segment"
    for _, arrs in staged:
        k, v, ks, vs, lens = arrs
        assert k.dtype == np.int8 and v.dtype == np.int8
        assert ks is not None and vs is not None
        assert ks.dtype == np.float32 and vs.dtype == np.float32


# -- verify-on-fetch: rot -> quarantine -> recompute only the victim ----------

def test_stream_rot_quarantines_and_recomputes_victim_page():
    """Mid-stream tier rot: the traveling checksum catches the rotted
    page at pin time, the pool quarantines that entry, and the streamer
    recomputes ONLY the victim page from its token span — the stream
    continues token-identically."""
    expect = oracle_engine().generate(PROMPT, GREEDY, "a")
    eng = stream_engine()
    eng.add_request(EngineRequest("r", PROMPT, GREEDY))
    out = []
    while eng.has_work() and len(out) < 4:
        drive(eng, out)
    q0 = STREAM_STATS.pages_quarantined
    r0 = STREAM_STATS.pages_recomputed
    # exactly ONE tier read rots; everything after reads clean
    REGISTRY.arm("offload.read_tier",
                 FaultSchedule(0, [FaultSpec("corrupt", p=1.0, n=1)]))
    while eng.has_work():
        drive(eng, out)
    assert out == expect
    assert STREAM_STATS.pages_quarantined - q0 == 1
    assert STREAM_STATS.pages_recomputed - r0 == 1


# -- preempt / resume / migration ---------------------------------------------

def test_stream_preempt_resume_identity():
    """Preempting a partially-streamed sequence spills its sealed pages,
    drops the unsealed tail, and resumes by replaying committed tokens
    WITHOUT re-emitting them; the final stream matches the oracle."""
    expect = oracle_engine().generate(PROMPT, GREEDY, "a")
    eng = stream_engine()
    eng.add_request(EngineRequest("r", PROMPT, GREEDY))
    out = []
    while eng.has_work() and len(out) < 5:
        drive(eng, out)
    seq = eng.scheduler.stream_active[0]
    ss = eng._streamer.record(seq)
    eng._streamer.preempt(seq)
    assert not ss.resident, "preempt must release every device page"
    assert ss.n_kv == ss.sealed_pages * PAGE
    p0 = STREAM_STATS.pages_promoted
    eng._streamer.resume_hot_prefix(ss)
    assert STREAM_STATS.pages_promoted - p0 > 0
    assert all(lg in ss.resident
               for lg in range(min(2, ss.sealed_pages)))  # hot prefix back
    while eng.has_work():
        drive(eng, out)
    assert out == expect


def test_stream_export_import_migration_identity():
    """export_seq after preempt yields a JSON-serializable record (pages
    stay content-addressed in the tiers); importing it restores the
    stream, which replays silently and continues oracle-identically —
    the aggregated leg of the disagg/migration handoff (the pool service
    moves the tier bytes between hosts)."""
    expect = oracle_engine().generate(PROMPT, GREEDY, "a")
    eng = stream_engine()
    eng.add_request(EngineRequest("r", PROMPT, GREEDY))
    out = []
    while eng.has_work() and len(out) < 5:
        drive(eng, out)
    seq = eng.scheduler.stream_active[0]
    eng._streamer.preempt(seq)
    record = json.loads(json.dumps(eng._streamer.export_seq(seq)))
    assert record["output"] == out
    # drop the live record entirely; import must rebuild it
    eng._streamer._seqs.pop("r")
    ss = eng._streamer.import_seq(seq, record)
    assert ss.n_kv == record["n_kv"] and ss.hashes == record["hashes"]
    while eng.has_work():
        drive(eng, out)
    assert out == expect


# -- spill policy units --------------------------------------------------------

def test_policy_observe_normalizes_flash_mass():
    # beta=0 -> the EWMA IS the last observation; masses l*exp(m - M)
    # normalize to 3/4, 1/4
    pol = StreamPolicy(hot_pages=0, beta=0.0)
    ewma = [1.0, 1.0]
    pol.observe(ewma, [0, 1], np.array([0.0, 0.0]), np.array([3.0, 1.0]))
    np.testing.assert_allclose(ewma, [0.75, 0.25])


def test_policy_ewma_folds_with_beta():
    pol = StreamPolicy(hot_pages=0, beta=0.5)
    ewma = [1.0]
    pol.observe(ewma, [0], np.array([0.0]), np.array([2.0]))
    # single page: normalized mass 1.0 -> 0.5 * 1.0 + 0.5 * 1.0
    np.testing.assert_allclose(ewma, [1.0])
    ewma = [0.0]
    pol.observe(ewma, [0], np.array([0.0]), np.array([2.0]))
    np.testing.assert_allclose(ewma, [0.5])


def test_policy_victim_lowest_mass_outside_hot_prefix():
    pol = StreamPolicy(hot_pages=2)
    ewma = [0.01, 0.02, 0.9, 0.1, 0.5]
    # pages 0/1 are hot-prefix-protected despite the lowest mass
    assert pol.victim(ewma, [0, 1, 2, 3, 4]) == 3
    # ties break toward the OLDEST logical page
    assert pol.victim([0.0, 0.0, 0.5, 0.5, 0.5], [2, 3, 4]) == 2
    # a fully-hot candidate set must still produce a victim
    assert pol.victim(ewma, [0, 1]) == 0
    assert pol.victim(ewma, []) is None


def test_policy_fresh_pages_protected_in_live_stream():
    """End-to-end: the tail-adjacent pages (freshest, EWMA starts at 1.0)
    stay resident while middle-of-context pages spill first."""
    eng = stream_engine()
    eng.generate(PROMPT, GREEDY, "a")
    # stream finished: release freed the pages, but the stats prove
    # spills happened while the stream ran
    assert STREAM_STATS.pages_spilled > 0


# -- admission rules -----------------------------------------------------------

def test_stream_admission_routing_and_rejections():
    eng = stream_engine()
    # a context that fits the resident budget never streams
    small = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    eng.add_request(EngineRequest("small", [1, 2, 3, 4], small))
    assert not eng.scheduler.stream_active
    while eng.has_work():
        eng.step()
    # plans the streamer cannot model are rejected at admission
    with pytest.raises(ValueError, match="logprobs"):
        eng.add_request(EngineRequest(
            "lp", PROMPT, SamplingParams(max_tokens=16, logprobs=1,
                                         ignore_eos=True)))
    with pytest.raises(ValueError, match="penalt"):
        eng.add_request(EngineRequest(
            "rp", PROMPT, SamplingParams(max_tokens=16,
                                         repetition_penalty=1.2,
                                         ignore_eos=True)))


def test_stream_config_validation():
    with pytest.raises(ValueError, match="host_pages"):
        stream_engine(host_pages=0)
