"""Cross-host KV pool service failure surface (ISSUE 17).

The cluster contract: pool pages replicate across R ring owners, and
every failure on the remote path — a host death mid-fetch, a membership
change racing a rebalance, rot on one replica, a dead owner at publish
time — degrades to failover or recompute, never to wrong tokens, a
dropped stream, or a stale-epoch write landing. Placement itself is
pinned too: the ring is deterministic, balanced within the vnode bound,
and moves a minimal key fraction on join.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.kv_cache import page_hash, tokens_hash
from dynamo_tpu.engine.kv_pool import POOL_STATS, PoolQuantMismatch
from dynamo_tpu.engine.pool_service import (
    REMOTE_STATS, RING_STATS, ClusterKvPool, KvPoolHost,
    PoolHostUnavailable,
)
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.runtime.faults import REGISTRY, FaultSchedule, FaultSpec
from dynamo_tpu.runtime.integrity import STATS as INTEGRITY
from dynamo_tpu.runtime.placement import (
    HashRing, PoolMembership, pool_host_instance_id,
)

# same tiny geometry as tests/test_kv_pool.py (jax-cache hits across files)
CFG = ModelConfig(dtype="float32", max_model_len=256)
PAGE = 8
PROMPT = list(range(10, 42))   # 4 pages; the walk matches the 3 full ones
GREEDY = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
SAMPLED = SamplingParams(max_tokens=4, temperature=0.9, top_k=8,
                         seed=1234, ignore_eos=True)


@pytest.fixture(autouse=True)
def clean_state():
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    POOL_STATS.reset()
    REMOTE_STATS.reset()
    RING_STATS.reset()
    yield
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    POOL_STATS.reset()
    REMOTE_STATS.reset()
    RING_STATS.reset()


def arm(site, *specs, seed=0):
    REGISTRY.arm(site, FaultSchedule(seed, list(specs)))


def make_engine(pool=None, wid="", num_pages=32, kv_quant=""):
    eng = NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=2,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=256, kv_quant=kv_quant), seed=0)
    if pool is not None:
        eng.attach_kv_pool(pool, wid or "w")
    return eng


def publish_all(eng):
    eng.drain_kv_events()
    eng._pool_stream.drain()


def make_cluster(n_hosts=3, replicas=2, capacity_pages=64,
                 disk_capacity_pages=0, tmpdir=None):
    cl = ClusterKvPool(replicas=replicas)
    for i in range(n_hosts):
        hid = f"ph{i}"
        cl.add_host(KvPoolHost(
            hid, capacity_pages=capacity_pages,
            disk_capacity_pages=disk_capacity_pages,
            disk_dir=f"{tmpdir}/{hid}" if tmpdir else None))
    cl.run_rebalance()   # drain the join enqueues (nothing resident yet)
    return cl


def seeded_cluster(prompt=PROMPT, kv_quant="", **kw):
    """A cluster holding `prompt`'s pages, published by worker A."""
    cl = make_cluster(**kw)
    a = make_engine(cl, "A", kv_quant=kv_quant)
    a.generate(prompt, GREEDY, "seed-a")
    publish_all(a)
    a.close()
    return cl


def page_arrays(seed=0, shape=(2, 2, 2, 4)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


# -- placement ring unit tests ------------------------------------------------

def test_ring_determinism_across_instances():
    """Same membership (any insertion order) -> same owners; placement
    must agree across processes without coordination."""
    r1, r2 = HashRing(vnodes=32), HashRing(vnodes=32)
    for h in ("a", "b", "c"):
        r1.add(h)
    for h in ("c", "a", "b"):
        r2.add(h)
    for k in range(500):
        assert r1.owners_for(k) == r2.owners_for(k)
    assert r1.owners_for(123) == r1.owners_for(123)   # stable re-ask


def test_ring_replicas_distinct_and_bounded_by_membership():
    r = HashRing(vnodes=16, replicas=3)
    r.add("a")
    assert r.owners_for(7) == ["a"]          # R degrades to hosts
    r.add("b"); r.add("c"); r.add("d")
    for k in range(200):
        owners = r.owners_for(k)
        assert len(owners) == 3
        assert len(set(owners)) == 3         # distinct hosts
    assert r.owners_for(5, r=1)[0] == r.owners_for(5)[0]   # primary stable


def test_ring_balance_bound():
    """Virtual nodes bound skew: with 64 vnodes/host no host owns more
    than ~2x its fair share of primary assignments."""
    r = HashRing(vnodes=64)
    for h in ("a", "b", "c", "d"):
        r.add(h)
    counts = {h: 0 for h in ("a", "b", "c", "d")}
    n = 4000
    for k in range(n):
        counts[r.lookup(k)] += 1
    fair = n / 4
    for h, c in counts.items():
        assert 0.5 * fair < c < 2.0 * fair, (h, counts)


def test_ring_minimal_movement_on_join():
    """Consistent hashing's point: a join steals only the arcs it lands
    on — at most ~the joiner's fair share of keys moves primary."""
    r = HashRing(vnodes=64)
    for h in ("a", "b", "c"):
        r.add(h)
    before = {k: r.lookup(k) for k in range(3000)}
    epoch_before = r.epoch
    r.add("d")
    assert r.epoch == epoch_before + 1       # membership bumps the epoch
    moved = sum(1 for k, h in before.items() if r.lookup(k) != h)
    # fair share is 1/4; allow slack for vnode granularity
    assert moved / 3000 < 0.40, moved
    # every moved key moved TO the joiner (nothing shuffled between
    # incumbents — the minimal-movement property)
    for k, h in before.items():
        now = r.lookup(k)
        if now != h:
            assert now == "d"


def test_ring_epoch_bumps_on_every_membership_change():
    r = HashRing()
    assert r.epoch == 0
    assert r.add("a") and r.epoch == 1
    assert not r.add("a") and r.epoch == 1   # no-op: no bump
    assert r.add("b") and r.epoch == 2
    assert r.remove("a") and r.epoch == 3
    assert not r.remove("a") and r.epoch == 3


def test_membership_watch_feed_joins_and_leaves_at_event_time():
    m = PoolMembership()
    events = []
    m.on_change(lambda kind, host, epoch: events.append((kind, host, epoch)))
    m.on_instance("put", pool_host_instance_id("h1"), {})
    m.on_instance("put", "worker-7", {})      # non-pool instance: ignored
    m.on_instance("put", pool_host_instance_id("h2"), {})
    assert set(m.live_hosts()) == {"h1", "h2"}
    m.on_instance("delete", pool_host_instance_id("h1"), {})
    assert set(m.live_hosts()) == {"h2"}
    assert events == [("join", "h1", 1), ("join", "h2", 2),
                      ("leave", "h1", 3)]


# -- replica failover ---------------------------------------------------------

def test_replica_failover_mid_fetch_token_identity():
    """THE failover contract (acceptance): a pool host dies mid-fetch
    (after page 1 committed, before page 2's fetch — the watch delete
    has NOT landed, so the dead host is still a ring member), the walk
    fails over to the surviving replica at page granularity, and tokens
    are identical to an all-local oracle under greedy AND seeded
    sampling. Zero dropped streams: every page still fetches."""
    oracle = make_engine()
    expect_g = oracle.generate(PROMPT, GREEDY, "og")
    expect_s = oracle.generate(PROMPT, SAMPLED, "os")

    for params, expect, tag in ((GREEDY, expect_g, "g"),
                                (SAMPLED, expect_s, "s")):
        REMOTE_STATS.reset()
        cl = seeded_cluster()
        # drop exactly the 3rd fetch ATTEMPT (= page 2's first-replica
        # try: one attempt per page while everyone is healthy)
        arm("pool.remote_fetch", FaultSpec("fail_n", n=1, skip=2))
        b = make_engine(cl, "B" + tag)
        assert b.generate(PROMPT, params, "b" + tag) == expect
        # all 3 matched pages fetched — the killed attempt failed OVER,
        # it did not fall back to recompute
        assert b.scheduler.pool_fetched_pages == 3
        assert REMOTE_STATS.fetch_pages == 3
        assert REMOTE_STATS.fetch_failovers == 1
        assert REMOTE_STATS.fetch_exhausted == 0
        REGISTRY.disarm()
        b.close()
    oracle.close()


def test_dead_host_failover_whole_walk():
    """A host killed BEFORE the fetch walk (no watch delete yet: still
    a ring member) makes every page it primaries fail over — the walk
    completes from the replicas, token-identical."""
    expect = make_engine().generate(PROMPT, GREEDY, "o")
    cl = seeded_cluster()
    # kill the primary owner of the FIRST page without membership change
    h0 = page_hash(0, PROMPT[:PAGE])
    primary = cl.membership.owners_for(h0)[0]
    cl._hosts[primary].kill()
    b = make_engine(cl, "B")
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert b.scheduler.pool_fetched_pages == 3
    assert REMOTE_STATS.fetch_failovers >= 1    # h0 (at least) hopped
    assert REMOTE_STATS.fetch_exhausted == 0
    b.close()


def test_all_replicas_exhausted_salvages_to_recompute():
    """Every owner dead: the fetch returns None, the walk breaks, the
    tail recomputes — exactly the in-process salvage contract (latency,
    never tokens)."""
    expect = make_engine().generate(PROMPT, GREEDY, "o")
    cl = seeded_cluster()
    h0 = page_hash(0, PROMPT[:PAGE])
    for h in list(cl._hosts.values()):
        h.kill()
    # a direct fetch walks every (dead) replica and gives up cleanly
    assert cl.fetch(h0) is None
    assert REMOTE_STATS.fetch_exhausted == 1
    # e2e: the containment facade already reports the pages gone (no
    # alive holder), so the engine recomputes without even fetching
    b = make_engine(cl, "B")
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert b.scheduler.pool_fetched_pages == 0
    b.close()


def test_rot_on_one_replica_quarantines_that_replica_only():
    """Corrupt the first replica attempt: THAT replica quarantines the
    page (removed there, never served), the fetch succeeds from the
    next replica, and the sibling copy survives."""
    expect = make_engine().generate(PROMPT, GREEDY, "o")
    cl = seeded_cluster()
    h0 = page_hash(0, PROMPT[:PAGE])
    owners_before = cl.owner_hosts(h0)
    assert len(owners_before) == 2
    # corrupt exactly the first fetch attempt (= page 0, replica 0)
    arm("pool.remote_fetch", FaultSpec("corrupt", p=1.0, n=1))
    b = make_engine(cl, "B")
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert b.scheduler.pool_fetched_pages == 3     # failover, not recompute
    assert REMOTE_STATS.fetch_failovers == 1
    assert INTEGRITY.quarantined == 1
    REGISTRY.disarm()
    # the rotten replica dropped its copy; the sibling still holds it
    assert len(cl.owner_hosts(h0)) == 1
    assert h0 in cl
    b.close()


# -- epoch fencing ------------------------------------------------------------

def test_ring_epoch_stale_write_fence():
    """A write computed under an old membership epoch is rejected BY
    NAME on the serving host and counted — it can never land (the
    alloc_epoch zombie-sender discipline, applied to placement)."""
    cl = make_cluster(n_hosts=2)
    arr = page_arrays()
    stale_epoch = cl.membership.epoch
    target = cl.membership.owners_for(0x42)[0]
    host = cl._hosts[target]
    # membership changes: the captured epoch is now stale
    cl.membership.join("late-joiner")
    r = host.publish_page("w1", 0x42, 0, 0x1, arr,
                          ring_epoch=stale_epoch)
    assert r == "stale-epoch"
    assert REMOTE_STATS.stale_epoch_rejected == 1
    assert REMOTE_STATS.stale_epoch_landed == 0
    assert not host.contains(0x42)               # nothing landed
    # the same write under the CURRENT epoch lands
    assert host.publish_page("w1", 0x42, 0, 0x1, arr,
                             ring_epoch=cl.membership.epoch) == "new"


def test_cluster_publish_rechecks_epoch_per_publish():
    """ClusterKvPool.publish captures the epoch at call time, so an
    ordinary publish after a membership change lands (fresh epoch) —
    the fence only stops writers that DON'T recheck."""
    cl = make_cluster(n_hosts=3)
    cl.membership.leave("ph2")
    assert cl.publish("w1", 0x7, 0, 0x1, page_arrays()) == "new"
    assert REMOTE_STATS.stale_epoch_rejected == 0
    assert 0x7 in cl


# -- quorum publish -----------------------------------------------------------

def test_quorum_1_publish_under_one_dead_owner():
    """R=2 with one owner dead: the publish lands on the survivor
    (quorum 1 — availability), is counted quorum-degraded, fetches
    fine, and the repair pass restores R once membership recovers."""
    cl = make_cluster(n_hosts=2)
    sh = 0x1234
    dead = cl.membership.owners_for(sh)[0]
    cl._hosts[dead].kill()          # dead but still a member (no watch yet)
    assert cl.publish("w1", sh, 0, 0x9, page_arrays()) == "new"
    assert REMOTE_STATS.publish_quorum_degraded == 1
    assert cl.fetch(sh) is not None               # served by the survivor
    # watch delete lands -> re-replication target is min(R, hosts)=1
    cl.kill_host(dead)
    assert cl.run_rebalance()["under_replicated"] == 0


def test_publish_all_owners_unreachable_returns_unavailable():
    cl = make_cluster(n_hosts=2)
    for h in cl._hosts.values():
        h.partition(True)
    assert cl.publish("w1", 0x5, 0, 0x1, page_arrays()) == "unavailable"
    assert 0x5 not in cl


def test_partitioned_host_fetch_fails_over_and_quorum_holds():
    """Partition (unreachable, still a member): fetchers fail over past
    it, publishes land on the reachable owner — and NO rebalance runs,
    because membership never changed."""
    cl = make_cluster(n_hosts=2)
    sh = 0x777
    assert cl.publish("w1", sh, 0, 0x1, page_arrays()) == "new"
    part = cl.membership.owners_for(sh)[0]
    cl.partition_host(part)
    assert cl.fetch(sh) is not None
    assert REMOTE_STATS.fetch_failovers == 1
    # a NEW publish still lands (quorum 1) and counts degraded
    assert cl.publish("w1", 0x778, 0, 0x1, page_arrays(1)) == "new"
    assert REMOTE_STATS.publish_quorum_degraded >= 1
    assert cl.run_rebalance()["copied"] == 0      # membership unchanged
    cl.partition_host(part, False)                # heal
    assert cl.fetch(sh) is not None


# -- rebalance conservation ---------------------------------------------------

def _publish_n(cl, n, source="w1"):
    hashes = []
    for i in range(n):
        sh = 0x1000 + i
        assert cl.publish(source, sh, 0, i, page_arrays(i)) == "new"
        hashes.append(sh)
    return hashes


def test_leave_rebalance_restores_replication():
    """Host leave: survivors re-replicate from their own copies until
    every entry is ≥ min(R, hosts)-sourced — conservation under churn."""
    cl = make_cluster(n_hosts=3)
    hashes = _publish_n(cl, 24)
    victim = cl.membership.live_hosts()[0]
    cl.kill_host(victim)
    # bounded convergence: small budget forces multiple paced passes
    for _ in range(20):
        if cl.run_rebalance(budget=4)["under_replicated"] == 0:
            break
    for sh in hashes:
        assert len(cl.owner_hosts(sh)) >= 2, hex(sh)
        assert cl.fetch(sh) is not None
    assert RING_STATS.under_replicated == 0
    assert RING_STATS.rebalanced_pages > 0


def test_join_rebalance_amortized_handoff():
    """Host join: the new owner receives its owed entries under the
    bounded budget; after convergence every entry is held by its CURRENT
    ring owners."""
    cl = make_cluster(n_hosts=2)
    hashes = _publish_n(cl, 24)
    newcomer = KvPoolHost("ph-new", capacity_pages=64)
    cl.add_host(newcomer)
    for _ in range(20):
        if cl.run_rebalance(budget=6)["under_replicated"] == 0:
            break
    for sh in hashes:
        owners = cl.membership.owners_for(sh)
        for hid in owners:
            assert cl._hosts[hid].contains(sh), (hex(sh), hid)
    assert len(newcomer) > 0                     # it actually took work


def test_rebalance_copy_faults_are_repaired_next_pass():
    """pool.rebalance drops skip copies without losing them: the next
    pass re-finds the gap (repair is idempotent)."""
    cl = make_cluster(n_hosts=3)
    hashes = _publish_n(cl, 12)
    cl.kill_host(cl.membership.live_hosts()[-1])
    arm("pool.rebalance", FaultSpec("drop", p=0.5))
    for _ in range(30):
        if cl.run_rebalance(budget=8)["under_replicated"] == 0:
            break
    REGISTRY.disarm()
    for sh in hashes:
        assert len(cl.owner_hosts(sh)) >= 2
    assert REMOTE_STATS.stale_epoch_landed == 0


def test_membership_change_mid_rebalance_fences_inflight_copies():
    """A leave landing between a rebalance's scan and its copies: the
    copies carry the scan-time epoch, the hosts fence them, and the
    next pass converges under the new membership — no entry lost, no
    stale write landed."""
    cl = make_cluster(n_hosts=3)
    hashes = _publish_n(cl, 10)
    cl.kill_host(cl.membership.live_hosts()[0])
    # sabotage: bump membership as a side effect of the first copy, by
    # hooking the first target host's publish
    fired = {"done": False}
    for h in cl._hosts.values():
        orig = h.publish_page

        def hooked(*a, _orig=orig, **kw):
            if not fired["done"]:
                fired["done"] = True
                cl.membership.join("ghost")      # epoch bump mid-pass
                cl.membership.leave("ghost")     # (and a second one)
            return _orig(*a, **kw)

        h.publish_page = hooked
    first = cl.run_rebalance(budget=100)
    assert fired["done"]
    # every copy after the sabotage was fenced, none landed stale
    assert REMOTE_STATS.stale_epoch_rejected >= 1
    assert REMOTE_STATS.stale_epoch_landed == 0
    for h in cl._hosts.values():                 # drop the hooks
        if "hooked" in repr(h.publish_page):
            h.publish_page = h.publish_page.__defaults__[0] \
                if False else type(h).publish_page.__get__(h)
    for _ in range(20):
        if cl.run_rebalance(budget=100)["under_replicated"] == 0:
            break
    for sh in hashes:
        assert len(cl.owner_hosts(sh)) >= 2
        assert cl.fetch(sh) is not None


# -- NVMe tier ----------------------------------------------------------------

def test_disk_spill_and_promote_with_traveling_checksum(tmp_path):
    """RAM-capacity evictions spill to the NVMe tier with the traveling
    checksum; a later fetch promotes back, verified."""
    cl = make_cluster(n_hosts=1, replicas=1, capacity_pages=2,
                      disk_capacity_pages=8, tmpdir=str(tmp_path))
    hashes = _publish_n(cl, 6)
    assert REMOTE_STATS.disk_spills >= 4
    for sh in hashes:                            # all still fetchable
        assert cl.fetch(sh) is not None
    assert REMOTE_STATS.disk_hits >= 4


def test_nvme_tier_rot_quarantine(tmp_path):
    """At-rest rot in the pool-side NVMe tier: DiskKvPool.take's verify
    (offload.read_tier failpoint) quarantines the entry — never served,
    counted, and the fetch degrades to a miss (recompute), exactly the
    offload-tier contract promoted pool-side."""
    cl = make_cluster(n_hosts=1, replicas=1, capacity_pages=2,
                      disk_capacity_pages=8, tmpdir=str(tmp_path))
    hashes = _publish_n(cl, 5)
    spilled = [sh for sh in hashes
               if sh in cl._hosts["ph0"]._disk_meta]
    assert spilled
    arm("offload.read_tier", FaultSpec("corrupt", p=1.0, n=1))
    assert cl.fetch(spilled[0]) is None          # quarantined, not served
    REGISTRY.disarm()
    assert REMOTE_STATS.disk_quarantined == 1
    assert INTEGRITY.quarantined >= 1
    assert spilled[0] not in cl


def test_disk_tier_preserves_kv_quant_mode(tmp_path):
    """Quantized pages spill and promote in their stored representation;
    a cross-mode fetch from the disk tier is rejected by name."""
    cl = make_cluster(n_hosts=1, replicas=1, capacity_pages=1,
                      disk_capacity_pages=8, tmpdir=str(tmp_path))
    k = np.ones((2, 2, 2, 4), np.int8)
    v = np.ones((2, 2, 2, 4), np.int8)
    ks = np.ones((2, 2, 2), np.float32)
    vs = np.ones((2, 2, 2), np.float32)
    assert cl.publish("w1", 0xA, 0, 1, (k, v, ks, vs),
                      mode="int8") == "new"
    assert cl.publish("w1", 0xB, 0, 2, (k, v, ks, vs),
                      mode="int8") == "new"      # spills 0xA to disk
    assert 0xA in cl._hosts["ph0"]._disk_meta
    with pytest.raises(PoolQuantMismatch):
        cl._hosts["ph0"].fetch_page(0xA, mode="")
    got = cl.fetch(0xA, mode="int8")
    assert got is not None and len(got) == 4     # scales rode along


# -- facade / events ----------------------------------------------------------

def test_cluster_pool_is_sharedkvpool_compatible_for_the_engine():
    """attach_kv_pool/_pool_claim/prefetch/publish-stream all run
    against the cluster facade unchanged (checksum-verified at claim
    like the in-process pool)."""
    cl = seeded_cluster()
    assert len(cl) >= 3          # the 3 matched pages (+ any tail page)
    b = make_engine(cl, "B")
    warmed = b.prefetch_pool_pages(PROMPT)
    assert warmed == 4           # all 4 full pages of PROMPT warm locally
    b.close()


def test_evict_source_drops_single_source_entries_cluster_wide():
    cl = make_cluster(n_hosts=2)
    _publish_n(cl, 4, source="w1")
    cl.drain_events("w1")
    assert cl.evict_source("w1") == 4
    assert len(cl) == 0
    # no removed events to the dead source itself
    assert cl.drain_events("w1") == []


def test_stored_events_ride_once_per_source():
    cl = make_cluster(n_hosts=3)
    cl.publish("w1", 0x1, 0, 0x10, page_arrays())
    cl.publish("w1", 0x1, 0, 0x10, page_arrays())     # dup: no new event
    cl.note_source("w2", 0x1, 0, 0x10)
    ev1 = cl.drain_events("w1")
    ev2 = cl.drain_events("w2")
    assert ev1 == [("stored", 0, 0x1, 0, 0x10)]
    assert ev2 == [("stored", 0, 0x1, 0, 0x10)]


def test_note_source_skips_unreachable_owner():
    """The dedup fast path counts only REACHABLE owners: a killed or
    partitioned host must not vouch for bytes it cannot serve — a
    'stored' answer with no live holder would price routes on a prefix
    whose every fetch burns a doomed replica walk into recompute."""
    cl = make_cluster(n_hosts=2)
    cl.publish("w1", 0x1, 0, 0x10, page_arrays())
    for h in cl._hosts.values():
        h.partition(True)
    assert cl.note_source("w2", 0x1, 0, 0x10) is False
    assert cl.drain_events("w2") == []     # no stored event emitted
    for h in cl._hosts.values():           # heal: owners vouch again
        h.partition(False)
    assert cl.note_source("w2", 0x1, 0, 0x10) is True
    assert cl.drain_events("w2") == [("stored", 0, 0x1, 0, 0x10)]


# -- concurrency regressions --------------------------------------------------

def test_concurrent_capacity_evictions_no_cross_host_deadlock():
    """ABBA regression: a capacity eviction reports the removed entry
    up to the cluster, whose globally-gone check scans the OTHER hosts.
    Two at-capacity hosts evicting concurrently used to each hold their
    own lock while waiting on the other's. The report is now delivered
    only after the evicting host's lock is released, so a publish storm
    across tiny no-disk hosts must always terminate."""
    import threading
    cl = make_cluster(n_hosts=2, replicas=1, capacity_pages=1)
    errs = []

    def storm(wid, base):
        try:
            for i in range(60):
                cl.publish(wid, base + i, 0, 0x1, page_arrays(i % 4))
        except Exception as exc:   # pragma: no cover — diagnostics only
            errs.append(exc)

    ts = [threading.Thread(target=storm, args=(f"w{k}", 0x1000 * (k + 1)),
                           daemon=True) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    assert not any(t.is_alive() for t in ts)   # a hung thread == deadlock


def test_read_page_miss_after_concurrent_eviction_returns_none():
    """read_page re-locks after the verifying fetch; a concurrent
    publish can evict the just-read entry in that window — the
    rebalance-side read must answer None (the next pass re-finds the
    gap), never crash run_rebalance with a KeyError."""
    h = KvPoolHost("ph0", capacity_pages=4)
    assert h.publish_page("w1", 0x1, 0, 0x10, page_arrays()) == "new"
    orig = h.fetch_page

    def racing_fetch(seq_hash, mode=""):
        arrays = orig(seq_hash, mode)
        with h._mu:                    # concurrent publish evicts it
            h._entries.pop(seq_hash, None)
        return arrays

    h.fetch_page = racing_fetch
    assert h.read_page(0x1) is None


def test_publish_retries_once_when_membership_races_mid_publish():
    """The (epoch, owners) snapshot is atomic, but membership can still
    change between the snapshot and the writes — every owner then
    fences the stale epoch. publish re-resolves under the new
    membership and retries ONCE instead of reporting a healthy pool
    'unavailable' (and silently not caching the page)."""
    cl = make_cluster(n_hosts=2)
    real = cl.membership.owners_with_epoch
    calls = {"n": 0}

    def racing(key, r=None):
        calls["n"] += 1
        epoch, owners = real(key, r)
        if calls["n"] == 1:            # join/leave landed mid-publish
            return epoch - 1, owners
        return epoch, owners

    cl.membership.owners_with_epoch = racing
    assert cl.publish("w1", 0x9, 0, 0x1, page_arrays()) == "new"
    assert calls["n"] == 2
    assert REMOTE_STATS.stale_epoch_rejected >= 1
    assert REMOTE_STATS.stale_epoch_landed == 0
    assert 0x9 in cl


# -- disagg admission: lease re-arm (satellite) -------------------------------

def test_lease_rearm_before_multi_page_pool_claim_pins_one_fetcher():
    """A remote pool claim ladder longer than lease_s must not spawn a
    duplicate sender: the admission path touches the lease BEFORE the
    engine claim when the pool holds a multi-page prefix, so the item
    is never redelivered mid-fetch — exactly one fetcher."""
    from dynamo_tpu.disagg.protocols import RemotePrefillRequest
    from dynamo_tpu.disagg.queue import PrefillQueue
    from dynamo_tpu.disagg.worker import PrefillWorker
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    cl = seeded_cluster()

    class Eng:
        class cfg:
            page_size = PAGE
        kv_pool = cl

    class W:
        engine = Eng()

    def req(rid, tokens):
        return RemotePrefillRequest(
            engine_id="dec-0", request_id=rid, token_ids=tokens,
            page_ids=list(range(len(tokens) // PAGE + 1)), page_size=PAGE)

    async def main():
        plane = MemoryPlane()
        q = PrefillQueue(plane.messaging, "ns", "tiny")
        await q.enqueue(req("r1", PROMPT))
        got, token = await q.dequeue_leased(lease_s=0.2)
        w = PrefillWorker.__new__(PrefillWorker)
        w.worker = W()
        w.queue = q
        w.lease_s = 5.0
        # the re-arm fires (multi-page pool match) and extends the lease
        assert await w._touch_for_pool_claim(got, token) is True
        await asyncio.sleep(0.3)   # original 0.2s lease would have expired
        # NOT redelivered: the re-armed lease still covers the fetcher —
        # exactly one sender for this item
        assert await q.dequeue_leased(lease_s=1.0, timeout=0.05) is None
        await q.ack(token)

        # control: a single-page match is covered by the normal lease,
        # so no re-arm fires
        await q.enqueue(req("r2", PROMPT[:PAGE]))
        got2, tok2 = await q.dequeue_leased(lease_s=0.2)
        assert await w._touch_for_pool_claim(got2, tok2) is False
        await q.ack(tok2)

    asyncio.run(asyncio.wait_for(main(), 30))


# -- router: pool-host liveness (satellite regression) ------------------------

def test_split_pool_scores_zeroes_when_no_live_pool_host():
    """Dead pool HOSTS (ring membership empty) stop pool pricing at
    watch-event time even though the publishing workers are alive —
    the PR-4 corpse fence extended one layer down."""
    from dynamo_tpu.kv_router.indexer import MatchResult
    from dynamo_tpu.kv_router.router import KvRouter

    class FakeClient:
        def __init__(self, instances):
            self.instances = instances

    m = PoolMembership()
    router = KvRouter(object(), FakeClient({"w1": {}}), block_size=4,
                      pool_membership=m)
    # both pool hosts live: the (live-sourced) pool depth prices
    m.join("h1"); m.join("h2")
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3})
    assert router._split_pool_scores(overlap) == 3
    # the last pool host dies at watch-event time: pricing zeroes
    # immediately — no live member can serve any fetch
    m.on_instance("delete", pool_host_instance_id("h1"), {})
    m.on_instance("delete", pool_host_instance_id("h2"), {})
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3})
    assert router._split_pool_scores(overlap) == 0
    assert overlap.scores == {"w1": 1}   # pool scores still split out
