"""The canonical disagg example (examples/disagg) must start with one
command and serve a chat completion (VERDICT item 9 'Done' bar)."""
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_example_disagg_one_command_chat_completion(tmp_path):
    control = _free_port()
    http = _free_port()
    cfg_path = tmp_path / "cfg.json"
    # config.cpu.yaml's values, as JSON (pyyaml may be absent) with the
    # test's own ports
    cfg = {
        "Frontend": {"port": http},
        "DecodeWorker": {"model": "tiny", "page_size": 64,
                         "max_model_len": 2048, "num_pages": 64,
                         "max_slots": 4, "max_local_prefill_length": 10,
                         "max_prefill_queue_size": 2},
        "PrefillWorker": {"model": "tiny", "page_size": 64,
                          "max_model_len": 2048, "num_pages": 64,
                          "max_slots": 4},
    }
    cfg_path.write_text(json.dumps(cfg))
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    sup = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.sdk.serve",
         "examples.disagg.graph:Frontend", "-f", str(cfg_path),
         "--start-control-plane", "--control-port", str(control)],
        stdout=subprocess.PIPE, cwd=REPO, env=env, text=True)
    try:
        while True:
            line = sup.stdout.readline()
            assert line, "supervisor exited early"
            if line.startswith("READY graph="):
                break
        body = json.dumps({
            "model": "tiny", "stream": False, "max_tokens": 6,
            "messages": [{"role": "user",
                          "content": "a prompt long enough to go through "
                                     "the remote prefill path of the "
                                     "example deployment"}],
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        deadline = time.time() + 120
        last = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(req, timeout=90) as resp:
                    out = json.load(resp)
                break
            except Exception as e:  # http not up yet
                last = e
                time.sleep(1)
        else:
            raise AssertionError(f"completion never served: {last}")
        assert out["choices"][0]["message"]["content"] is not None
        assert out["choices"][0]["finish_reason"] in ("length", "stop")
    finally:
        sup.send_signal(signal.SIGINT)
        try:
            sup.wait(20)
        except subprocess.TimeoutExpired:
            sup.kill()
