"""AOT-compile the mixtral-8x7b serving plan on a virtual ep4 x tp2 mesh
and report per-device compiled memory (spawned by test_70b_memory.py;
prints one JSON line; --int8 switches on weight-only quantization of the
attention + stacked expert tensors, ops/quant.py).

Same method as aot_70b_child.py: ShapeDtypeStruct params via
jax.eval_shape, AOT lower+compile, per-device CompiledMemoryStats; the
RESIDENT set (sharded params + paged KV + step I/O net of donation) is
the cross-platform number.
"""
import dataclasses
import functools
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from dynamo_tpu.engine.config import get_model_config  # noqa: E402
from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.llama import AttnMetadata  # noqa: E402
from dynamo_tpu.ops.quant import quantize_params, quantize_shardings  # noqa: E402
from dynamo_tpu.parallel.mesh import make_mesh  # noqa: E402


def main():
    ep, tp = 4, 2
    cfg = get_model_config("mixtral-8x7b")
    if "--int8" in sys.argv:
        cfg = dataclasses.replace(cfg, quant="int8")
    mesh = make_mesh(ep=ep, tp=tp, devices=jax.devices()[:ep * tp])

    slots, page_size, ctx = 8, 64, 2048
    num_pages = slots * ctx // page_size
    pages_per_seq = ctx // page_size
    chunk = 128

    def make_params(k):
        p = llama.init_params(k, cfg)
        return quantize_params(p, cfg) if cfg.quant == "int8" else p

    params = jax.eval_shape(make_params, jax.random.PRNGKey(0))
    cache = jax.eval_shape(lambda: llama.init_cache(cfg, num_pages,
                                                    page_size))
    param_bytes = sum(np.prod(x.shape) * x.dtype.itemsize
                      for x in jax.tree.leaves(params))

    specs = llama.param_shardings(cfg)
    if cfg.quant == "int8":
        specs = quantize_shardings(specs, cfg)
    p_shd = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                         is_leaf=lambda x: isinstance(x, P))
    c_shd = NamedSharding(mesh, llama.cache_sharding(cfg))
    rep = NamedSharding(mesh, P())

    sds = jax.ShapeDtypeStruct

    def fwd(p, c, tokens, pos, pt, kl, wi):
        meta = AttnMetadata(positions=pos, page_table=pt, kv_lens=kl,
                            write_idx=wi)
        _, new_cache, _ = llama.forward(p, cfg, tokens, c, meta, mesh=mesh,
                                        with_aux=True)
        return new_cache

    compiled = jax.jit(
        fwd,
        in_shardings=(p_shd, {"k": c_shd, "v": c_shd},
                      rep, rep, rep, rep, rep),
        donate_argnums=(1,)).lower(
        params, cache,
        sds((slots, chunk), jnp.int32), sds((slots, chunk), jnp.int32),
        sds((slots, pages_per_seq), jnp.int32), sds((slots,), jnp.int32),
        sds((slots, chunk), jnp.int32)).compile()
    ma = compiled.memory_analysis()
    print(json.dumps({
        "mesh": f"ep{ep}xtp{tp}",
        "quant": cfg.quant or "bf16",
        "param_bytes_total": int(param_bytes),
        "prefill": {
            "resident": int(ma.argument_size_in_bytes
                            + ma.output_size_in_bytes
                            - ma.alias_size_in_bytes),
            "temp_cpu": int(ma.temp_size_in_bytes),
        },
    }))


if __name__ == "__main__":
    main()
