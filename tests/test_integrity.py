"""KV data-plane integrity (runtime/integrity.py): the contract that a
corrupted transfer or tier read may cost latency but can NEVER change
emitted tokens.

Coverage, one test per leg of the state machine (docs/RESILIENCE.md):

- corrupt ON THE WIRE (remote TCP transfer): decode-side verify rejects
  the chunk, the sender re-fetches from its still-authoritative device
  copy, tokens stay oracle-exact;
- PERSISTENT wire corruption: the bounded re-fetch budget exhausts, the
  remote path is abandoned (quarantine counted) and the decode side
  falls back to a LOCAL re-prefill — degraded latency, identical tokens;
- corrupt AT REST in the offload tiers (host DRAM slab, disk slab): the
  verify-on-fetch gate quarantines the entry, the prefix walk misses,
  the pages are recomputed — identical tokens, never served rot.

Faults are injected through the failpoint registry (seeded, replayable);
every test asserts both the token contract and the integrity counters
that surface on /metrics as llm_kv_integrity_*.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.faults import FaultSchedule, FaultSpec, REGISTRY
from dynamo_tpu.runtime.integrity import (
    STATS as INTEGRITY, IntegrityError, page_checksum,
)

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


@pytest.fixture(autouse=True)
def clean_state():
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    yield
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()


def arm(site, *specs, seed=0):
    REGISTRY.arm(site, FaultSchedule(seed, list(specs)))


def make_engine(num_pages=64, **kw):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=4,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=512, **kw), seed=0)


def _disagg_remote_stack_kvq(plane, integrity_retries=2):
    """Same stack as _disagg_remote_stack but with int8-KV engines on
    BOTH sides (the transfer contract requires matching kv_quant)."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer,
        PrefillQueue, PrefillWorker, RemoteTransferBackend,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker

    async def build():
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=8, model="tiny")
        decode = DisaggDecodeWorker(
            make_engine(kv_quant="int8"), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=30.0)
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        transfer = RemoteTransferBackend(
            plane.kv, integrity_retries=integrity_retries)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine(kv_quant="int8")), queue,
            transfer, plane.messaging)
        return decode, prefill, server, transfer

    return build()


_ORACLE = []


def oracle(prompt, params, rid):
    """Greedy expectations off ONE shared engine (deterministic; pages
    release at completion) — a fresh engine per expectation would pay
    the jit compile several times over in this file alone."""
    if not _ORACLE:
        _ORACLE.append(make_engine())
    return _ORACLE[0].generate(prompt, params, rid)


# -- checksum primitive --------------------------------------------------------

def test_page_checksum_is_deterministic_and_content_sensitive():
    k = np.arange(32, dtype=np.float32).reshape(4, 8)
    v = k + 1
    a = page_checksum(k, v)
    assert a == page_checksum(k.copy(), v.copy())
    flipped = k.copy()
    flipped.view(np.uint8)[3] ^= 0xFF
    assert page_checksum(flipped, v) != a
    assert page_checksum(v, k) != a      # order (k then v) matters


# -- corrupt on the wire: bounded re-fetch -------------------------------------

def _disagg_remote_stack(plane, integrity_retries=2):
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer,
        PrefillQueue, PrefillWorker, RemoteTransferBackend,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker

    async def build():
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=8, model="tiny")
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=30.0)
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        transfer = RemoteTransferBackend(
            plane.kv, integrity_retries=integrity_retries)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging)
        return decode, prefill, server, transfer

    return build()


def _pre(rid, prompt, max_tokens=6):
    from dynamo_tpu.protocols.common import PreprocessedRequest, \
        StopConditions
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


async def _drive(gen):
    toks, reasons = [], []
    async for frame in gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reasons.append(frame["finish_reason"])
    return toks, reasons


def test_wire_corruption_absorbed_by_refetch_tokens_identical():
    """A transient corruption (one seeded flip burst) on the transfer
    wire: the decode side's verify rejects the chunk, one re-fetch
    re-stages clean bytes, and the stream is token-identical — the
    corruption cost a round trip, nothing else."""
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = oracle(prompt, params, "oracle")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _disagg_remote_stack(
            plane)
        await decode.start()
        await prefill.start()
        # nbytes=16 spreads flips across the (pow2-padded) chunk so at
        # least one lands inside a real page's bytes; n=1 bounds the
        # burst to the first send — the re-fetch goes out clean
        arm("remote_transfer.fetch_page",
            FaultSpec("corrupt", p=1.0, n=1, nbytes=16))
        try:
            toks, reasons = await asyncio.wait_for(_drive(
                decode.generate(_pre("r1", prompt), Context("r1"))), 120)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, reasons

    toks, reasons = asyncio.run(main())
    assert toks == expect, (toks, expect)
    assert reasons == ["length"]
    assert INTEGRITY.mismatches >= 1, "corruption was never detected"
    assert INTEGRITY.refetches >= 1, "no re-fetch was attempted"
    assert INTEGRITY.quarantined == 0   # transient: absorbed, not abandoned
    assert INTEGRITY.reprefills == 0


def test_persistent_wire_corruption_falls_back_to_local_prefill():
    """EVERY transfer attempt corrupts: the bounded re-fetch budget
    exhausts, the sender abandons the remote path (pages quarantined,
    counted), the prefill item fails cleanly, and the decode side
    re-prefills LOCALLY — the client stream still finishes with
    oracle-exact tokens."""
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    prompt = list(range(40, 60))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = oracle(prompt, params, "oracle")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _disagg_remote_stack(
            plane, integrity_retries=1)
        await decode.start()
        await prefill.start()
        # unbounded (n=0) corruption: every send attempt rots on the wire
        arm("remote_transfer.fetch_page",
            FaultSpec("corrupt", p=1.0, n=0, nbytes=16))
        try:
            toks, reasons = await asyncio.wait_for(_drive(
                decode.generate(_pre("r2", prompt), Context("r2"))), 120)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, reasons, decode.remote_prefills, decode.local_prefills

    toks, reasons, remote, fallbacks = asyncio.run(main())
    assert toks == expect, (toks, expect)
    assert reasons == ["length"]
    assert remote == 1 and fallbacks == 1
    assert INTEGRITY.refetches >= 1       # the budget was actually spent
    assert INTEGRITY.quarantined >= 1     # then the source pages quarantined
    assert INTEGRITY.reprefills >= 1      # and the remote path abandoned


def test_kv_quant_wire_corruption_absorbed_by_refetch():
    """int8 KV pages over the disagg wire under a seeded corruption
    burst: checksums computed over the QUANTIZED bytes (values + scale
    rows, no dequant) catch the flip, one re-fetch re-stages clean
    bytes, and the stream is token-identical to the int8 local oracle —
    the acceptance bar's corrupt->refetch leg for quantized pages."""
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine(kv_quant="int8").generate(prompt, params, "kvq-o")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _disagg_remote_stack_kvq(
            plane)
        await decode.start()
        await prefill.start()
        arm("remote_transfer.fetch_page",
            FaultSpec("corrupt", p=1.0, n=1, nbytes=16))
        try:
            toks, reasons = await asyncio.wait_for(_drive(
                decode.generate(_pre("rq1", prompt), Context("rq1"))), 120)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, reasons

    toks, reasons = asyncio.run(main())
    assert toks == expect, (toks, expect)
    assert reasons == ["length"]
    assert INTEGRITY.mismatches >= 1
    assert INTEGRITY.refetches >= 1
    assert INTEGRITY.quarantined == 0
    assert INTEGRITY.reprefills == 0


# -- corrupt at rest: offload tiers --------------------------------------------

def test_host_tier_rot_quarantines_and_recomputes_tokens_identical():
    """A->B->A offload roundtrip with the host DRAM tier rotting at
    read time: the pin-time verify quarantines every touched entry, the
    prefix walk misses, pages are recomputed — tokens identical, rot is
    never served."""
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    prompt_a = list(range(10, 34))    # 3 pages
    prompt_b = list(range(100, 140))  # 5 pages — evicts A's pages
    expect_a = oracle(prompt_a, params, "oracle-a")

    eng = make_engine(num_pages=8, host_pages=16)
    assert eng.generate(prompt_a, params, "a1") == expect_a
    eng.generate(prompt_b, params, "b")
    assert eng.host_pool.stats.offloaded > 0, "eviction must offload"
    # every read of the DRAM slab from here on surfaces at-rest rot
    arm("offload.read_tier", FaultSpec("corrupt", p=1.0, n=0))
    got_a2 = eng.generate(prompt_a, params, "a2")
    assert got_a2 == expect_a
    assert INTEGRITY.mismatches >= 1
    assert INTEGRITY.quarantined >= 1
    # the quarantined entries are really gone, not just skipped once
    REGISTRY.disarm()
    assert eng.host_pool.stats.onboarded == 0


def test_disk_tier_rot_quarantined_at_promotion(tmp_path):
    from dynamo_tpu.engine.offload import DiskKvPool
    pool = DiskKvPool(4, (2, 8), np.float32, str(tmp_path))
    page = np.arange(16, dtype=np.float32).reshape(2, 8)
    pool.put(0x1, page, page + 1)
    arm("offload.read_tier", FaultSpec("corrupt", p=1.0, n=1))
    assert pool.take(0x1) is None         # rot at read: quarantined
    assert INTEGRITY.quarantined == 1
    # a clean entry still promotes with its traveling checksum
    pool.put(0x2, page * 2, page * 3)
    got = pool.take(0x2)
    assert got is not None
    k, v, sum_ = got
    np.testing.assert_array_equal(k, page * 2)
    assert sum_ == page_checksum(page * 2, page * 3)


def test_spill_carries_checksum_so_dram_rot_cannot_launder(tmp_path):
    """The checksum travels DOWN on spill: a page that rots in DRAM and
    then spills to disk must still fail verification when promoted (the
    spill must not recompute a checksum over rotten bytes)."""
    from dynamo_tpu.engine.offload import HostKvPool
    pool = HostKvPool(1, (2, 8), np.float32)
    from dynamo_tpu.engine.offload import DiskKvPool
    pool.disk = DiskKvPool(4, (2, 8), np.float32, str(tmp_path))
    page = np.arange(16, dtype=np.float32).reshape(2, 8)
    pool.put(0xA, page, page)
    # rot the DRAM slab byte directly (at-rest corruption between
    # writes), then force a spill by inserting a second entry
    pool.k_slab[0].view(np.uint8)[0, 5] ^= 0xFF
    pool.put(0xB, page * 2, page * 2)     # evicts 0xA -> disk, rot and all
    assert pool.stats.disk_offloaded == 1
    # promotion verifies against the CAPTURE-time checksum: quarantined
    assert pool.get(0xA) is None
    assert INTEGRITY.quarantined == 1
    assert 0xA not in pool


def test_integrity_error_carries_pages():
    err = IntegrityError("transfer into 'dec-0'", [3, 7])
    assert err.pages == [3, 7]
    assert "dec-0" in str(err) and "3, 7" in str(err)
