"""Sharded parallel KV transfer (ISSUE 15): per-(shard, host)
chunk-committed streams for cross-mesh disagg.

The matrix the acceptance criteria name, per stream:

- e2e token identity (greedy + seeded-sampled) through N parallel
  streams, on single-device (head-split layout) AND tp=2 decode meshes;
- seeded cut of ONE stream at the first/middle/last chunk: only that
  stream's unacked tail is re-shipped, siblings never resend;
- sender death mid-transfer: the replacement sender's handshakes skip
  each stream's OWN committed frontier;
- a permanently dead single stream (others healthy): salvage charges
  exactly the MIN-frontier pages;
- stale-epoch fencing per stream after release+realloc;
- early decode gates on the min over per-stream frontiers (a straggler
  stream holds the gate);
- int8 kv_quant slices (values + scale rows sharded by the same plan);
- TransferCostModel group pricing (bytes split per shard, aggregate
  goodput = sum of per-link EWMAs, backlog per destination host).

Engines reuse the test_remote_transfer geometry for jax-cache hits.
"""
import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, PrefillQueue, PrefillWorker,
    RemoteTransferBackend, ShardedKvTransferGroup,
)
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.llm.worker import NativeEngineWorker
from dynamo_tpu.parallel.mesh import kv_shard_layout, make_mesh
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.integrity import XFER_STATS
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()


def make_engine(mesh=None, kv_quant=""):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512,
        kv_quant=kv_quant), mesh=mesh, seed=0)


# ONE oracle engine per module (tier-1 budget): oracle generation is
# deterministic and prefix reuse is exact, so sharing it across tests
# only warms its cache; expected outputs memoized per (prompt, params).
_ORACLE = {}
_EXPECT = {}


def expected(prompt, params, kv_quant=""):
    key = (tuple(prompt), params.max_tokens, params.temperature,
           params.top_k, params.top_p, params.seed, kv_quant)
    if key not in _EXPECT:
        eng = _ORACLE.get(kv_quant)
        if eng is None:
            eng = _ORACLE[kv_quant] = make_engine(kv_quant=kv_quant)
        _EXPECT[key] = eng.generate(prompt, params,
                                    f"o{len(_EXPECT)}")
    return _EXPECT[key]


def pre_request(rid, prompt, max_tokens=6, sampled=False):
    kw = {}
    if sampled:
        kw = dict(sampling={"temperature": 0.8, "top_k": 40,
                            "top_p": 0.95, "seed": 1234})
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True), **kw)


async def _drive(worker_gen):
    toks, reason = [], None
    async for frame in worker_gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reason = frame["finish_reason"]
    return toks, reason


async def _build_sharded_stack(plane, hosts=2, n_streams=2,
                               decode_mesh=None, prefill_mesh=None,
                               chunk_pages=1, kv_quant="",
                               transfer_cls=RemoteTransferBackend,
                               transfer_kw=None, early_decode=True):
    """Disagg stack over the sharded parallel transfer plane: a per-host
    endpoint group on the decode side, one stream per (shard, host)."""
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=8, model="tiny")
    decode = DisaggDecodeWorker(
        make_engine(decode_mesh, kv_quant), plane.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=60.0,
        early_decode=early_decode)
    group = await ShardedKvTransferGroup(
        decode, "dec-0", hosts=hosts, n_streams=n_streams).start()
    await group.register(plane.kv)
    transfer = transfer_cls(plane.kv, chunk_pages=chunk_pages,
                            window_chunks=1, **(transfer_kw or {}))
    prefill = PrefillWorker(
        NativeEngineWorker(make_engine(prefill_mesh, kv_quant)), queue,
        transfer, plane.messaging, dequeue_timeout_s=0.1)
    return decode, prefill, group, transfer


async def _teardown(decode, prefill, group, transfer):
    await prefill.stop()
    await decode.stop()
    await transfer.close()
    await group.stop()


def test_sharded_e2e_token_identical_greedy_and_sampled():
    """2 hosts x 2 shard streams: greedy AND seeded-sampled outputs are
    token-identical to the aggregated oracle; both per-host endpoints
    inject their slices; the transfer is counted as parallel."""
    prompt = list(range(100, 120))          # 3 pages -> 3 chunks/stream
    prompt2 = list(range(130, 150))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    sp = SamplingParams(max_tokens=6, temperature=0.8, top_k=40,
                        top_p=0.95, seed=1234, ignore_eos=True)
    expect2 = expected(prompt2, sp)
    p0 = XFER_STATS.parallel_transfers

    async def main():
        plane = MemoryPlane()
        decode, prefill, group, transfer = await _build_sharded_stack(plane)
        assert decode.kv_transfer_server is group
        assert group.n_streams == 2 and len(group.servers) == 2
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("r1", prompt).model_dump(
                    exclude_none=True), Context("r1"))), 60)
            toks2, reason2 = await asyncio.wait_for(_drive(
                decode.generate(
                    pre_request("r2", prompt2, sampled=True).model_dump(
                        exclude_none=True), Context("r2"))), 60)
        finally:
            await _teardown(decode, prefill, group, transfer)
        per_server_rx = [srv.received_pages for srv in group.servers]
        return toks, reason, toks2, reason2, per_server_rx

    toks, reason, toks2, reason2, per_server_rx = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert reason2 == "length" and toks2 == expect2
    # each endpoint injected its own stream's slice of every page
    assert all(rx >= 3 for rx in per_server_rx), per_server_rx
    assert XFER_STATS.parallel_transfers - p0 == 2


def test_sharded_e2e_on_tp2_decode_mesh():
    """The shard plan aligned with a REAL tp=2 decode mesh: slices land
    via the per-shard scatter, tokens match the single-device oracle
    (the mesh identity the pp/tp suites already pin, now through the
    sharded transfer plane)."""
    devs = jax.devices()
    assert len(devs) >= 2
    prompt = list(range(60, 80))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)

    async def main():
        plane = MemoryPlane()
        decode, prefill, group, transfer = await _build_sharded_stack(
            plane, hosts=2, n_streams=0,   # natural layout: tp shards
            decode_mesh=make_mesh(tp=2, devices=devs[:2]))
        assert group.n_streams == 2
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("t1", prompt).model_dump(
                    exclude_none=True), Context("t1"))), 60)
        finally:
            await _teardown(decode, prefill, group, transfer)
        return toks, reason

    toks, reason = asyncio.run(main())
    assert reason == "length" and toks == expect


def test_sharded_kv_quant_int8_e2e():
    """int8 engines both sides: the shard plan slices the scale rows
    with the values (shared leading axes), verify-on-fetch covers the
    quantized slice bytes, tokens match the int8 oracle."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params, kv_quant="int8")

    async def main():
        plane = MemoryPlane()
        decode, prefill, group, transfer = await _build_sharded_stack(
            plane, kv_quant="int8")
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("rq", prompt).model_dump(
                    exclude_none=True), Context("rq"))), 60)
        finally:
            await _teardown(decode, prefill, group, transfer)
        return toks, reason

    toks, reason = asyncio.run(main())
    assert reason == "length" and toks == expect


class CutOneStream(RemoteTransferBackend):
    """Deterministically cut ONE stream at one chunk index, once."""

    cut_stream = 1
    cut_chunk = 0

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.cuts = 0

    async def _chunk_gate(self, chunk_idx, stream=0):
        if (stream == self.cut_stream and chunk_idx == self.cut_chunk
                and self.cuts == 0):
            self.cuts += 1
            raise ConnectionResetError("seeded single-stream cut")
        await super()._chunk_gate(chunk_idx, stream)


@pytest.mark.parametrize("cut_chunk", [0, 1, 2])
def test_single_stream_cut_resumes_only_that_stream(cut_chunk):
    """A cut on stream 1 at the first/middle/last chunk: the stream
    reconnects, learns ITS OWN frontier, and re-ships only its unacked
    tail — stream 0 never re-sends a chunk, and the output is
    token-identical."""
    prompt = list(range(100, 120))          # 3 pages
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    XFER_STATS.per_stream.clear()
    r0 = XFER_STATS.resumes

    async def main():
        plane = MemoryPlane()
        CutOneStream.cut_chunk = cut_chunk
        decode, prefill, group, transfer = await _build_sharded_stack(
            plane, transfer_cls=CutOneStream)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("rc", prompt).model_dump(
                    exclude_none=True), Context("rc"))), 60)
        finally:
            await _teardown(decode, prefill, group, transfer)
        return toks, reason, transfer.cuts

    toks, reason, cuts = asyncio.run(main())
    assert reason == "length" and toks == expect and cuts == 1
    snap = XFER_STATS.stream_snapshot()
    s0 = snap["dec-0/h0#0"]
    s1 = snap["dec-0/h1#1"]
    # unique accounting: every page-slice crossed each stream exactly once
    assert s0["pages"] == 3 and s1["pages"] == 3
    assert s0["resumes"] == 0
    if cut_chunk > 0:
        # the cut stream resumed from its OWN nonzero frontier
        assert s1["resumes"] == 1
        assert XFER_STATS.resumes - r0 == 1
    assert s0["frontier"] == 3 and s1["frontier"] == 3


class StallStream(RemoteTransferBackend):
    """Stream `stall_stream` wedges forever at chunk >= `stall_chunk`:
    the worker driving it dies holding a part-committed transfer while
    its sibling stream completes."""

    stall_stream = 1
    stall_chunk = 2

    async def _chunk_gate(self, chunk_idx, stream=0):
        if stream == self.stall_stream and chunk_idx >= self.stall_chunk:
            await asyncio.Event().wait()
        await super()._chunk_gate(chunk_idx, stream)


def test_sender_death_replacement_resumes_each_stream_frontier():
    """Sender dies with stream 0 complete and stream 1 stalled at chunk
    2 of 5: the re-leased replacement opens BOTH streams, stream 0's
    handshake skips everything, stream 1 ships only its tail."""
    prompt = list(range(50, 90))            # 5 pages
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    XFER_STATS.per_stream.clear()
    r0 = XFER_STATS.resumes

    async def main():
        plane = MemoryPlane()
        decode, doomed_pf, group, doomed_tx = await _build_sharded_stack(
            plane, transfer_cls=StallStream)
        doomed_pf.lease_s = 0.5
        surv_tx = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                        window_chunks=1)
        survivor = PrefillWorker(
            NativeEngineWorker(make_engine()), doomed_pf.queue,
            surv_tx, plane.messaging, dequeue_timeout_s=0.1, lease_s=10.0)
        await decode.start()
        await doomed_pf.start()
        task = asyncio.create_task(_drive(
            decode.generate(pre_request("rd", prompt).model_dump(
                exclude_none=True), Context("rd"))))
        # wait until stream 0 commits everything and stream 1 stalls
        deadline = asyncio.get_event_loop().time() + 30

        def _epoch(dec):
            seq = dec.engine.scheduler.remote.get("rd")
            return seq.epoch if seq is not None else 0

        def stalled():
            f = group.stream_frontiers("rd", _epoch(decode))
            return f.get("dec-0/h0#0", 0) >= 5 \
                and f.get("dec-0/h1#1", 0) >= 2

        while not stalled():
            assert asyncio.get_event_loop().time() < deadline, \
                group.stream_frontiers("rd", _epoch(decode))
            await asyncio.sleep(0.02)
        await doomed_pf.stop()
        await survivor.start()
        toks, reason = await asyncio.wait_for(task, 120)
        redelivered = plane.messaging.redeliveries
        survivor_sent = surv_tx.sent_pages
        await survivor.stop()
        await decode.stop()
        await group.stop()
        await surv_tx.close()
        return toks, reason, redelivered, survivor_sent

    toks, reason, redelivered, survivor_sent = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert redelivered >= 1
    # the replacement shipped ONLY stream 1's tail (3 page-slices of 5;
    # stream 0's handshake skipped all 5) — per-stream frontiers, not
    # one shared frontier
    assert survivor_sent == 3, survivor_sent
    assert XFER_STATS.resumes - r0 >= 1


class DeadStream(RemoteTransferBackend):
    """Stream `dead_stream` fails permanently from chunk `dead_from`."""

    dead_stream = 1
    dead_from = 2

    async def _chunk_gate(self, chunk_idx, stream=0):
        if stream == self.dead_stream and chunk_idx >= self.dead_from:
            raise ConnectionResetError("stream link permanently dead")
        await super()._chunk_gate(chunk_idx, stream)


def test_dead_single_stream_salvages_min_frontier_pages():
    """Stream 1's link dies for good after committing 2 of 5 chunks
    while stream 0 completes: salvage must charge exactly the MIN
    frontier (2 pages) — the pages every stream committed — and
    re-prefill the rest; token-identical; the sibling stream is never
    the unit that decides (dynalint R20's aggregation contract)."""
    prompt = list(range(50, 90))            # 5 pages
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    s0 = XFER_STATS.salvaged_pages

    async def main():
        plane = MemoryPlane()
        decode, prefill, group, transfer = await _build_sharded_stack(
            plane, transfer_cls=DeadStream,
            transfer_kw=dict(link_retries=1))
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("rs", prompt).model_dump(
                    exclude_none=True), Context("rs"))), 120)
        finally:
            await _teardown(decode, prefill, group, transfer)
        return (toks, reason, decode.salvaged_prefills,
                decode.full_reprefills,
                decode.majority_committed_full_reprefills)

    toks, reason, salvaged, full, majority_full = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert salvaged == 1 and full == 0 and majority_full == 0
    # min over per-stream frontiers: stream 0 committed 5, stream 1
    # committed 2 -> salvage keeps exactly 2 pages
    assert XFER_STATS.salvaged_pages - s0 == 2


def test_stale_epoch_fenced_per_stream_after_realloc():
    """Release + re-allocate the same request id: a sender holding the
    OLD epoch is fenced on EVERY stream — no slice lands — while the
    new-epoch sender streams normally."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    async def main():
        plane = MemoryPlane()
        decode = NativeEngineWorker(make_engine())
        await decode.start()
        group = await ShardedKvTransferGroup(
            decode, "dec-0", hosts=2, n_streams=2).start()
        await group.register(plane.kv)
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1)
        prefill_eng = make_engine()
        st0 = XFER_STATS.stale_chunks
        try:
            alloc1 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            prefill_eng.add_request(
                EngineRequest("race", prompt, params, prefill_only=True))
            while prefill_eng.has_work():
                prefill_eng.step()
            pages = prefill_eng.extract_pages(
                prefill_eng.scheduler.parked["race"].pages)
            await decode.submit(lambda eng: eng.release_remote("race"))
            alloc2 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            assert alloc2.alloc_epoch > alloc1.alloc_epoch > 0
            with pytest.raises(RuntimeError, match="[Ss]tale"):
                await transfer.send_pages(
                    "dec-0", "race", alloc1.page_ids,
                    pages["k"], pages["v"],
                    alloc_epoch=alloc1.alloc_epoch)
            assert XFER_STATS.stale_chunks - st0 >= 1
            assert group.received_pages == 0
            # min-frontier sees nothing committed for the live epoch
            assert group.committed_frontier("race",
                                            alloc2.alloc_epoch) == 0
            await transfer.send_pages(
                "dec-0", "race", alloc2.page_ids,
                pages["k"], pages["v"], alloc_epoch=alloc2.alloc_epoch)
            assert group.committed_frontier(
                "race", alloc2.alloc_epoch) == len(alloc2.page_ids)
        finally:
            await transfer.close()
            await group.stop()
            await decode.stop()

    asyncio.run(main())


class SlowLastChunk(RemoteTransferBackend):
    """Stream 1 delays its FINAL chunk: the early-decode gate must hold
    on the min frontier until the straggler lands."""

    hold = None     # asyncio.Event set by the test to release the chunk
    total_chunks = 3

    async def _chunk_gate(self, chunk_idx, stream=0):
        if stream == 1 and chunk_idx == self.total_chunks - 1 \
                and self.hold is not None:
            await self.hold.wait()
        await super()._chunk_gate(chunk_idx, stream)


def test_early_decode_gate_waits_for_straggler_stream():
    """Early-decode overlap over sharded streams: the first token is
    emitted while BOTH streams are still in flight, but decode
    activation waits for the min frontier — a straggler stream holding
    one slice of the last page holds the gate; once it lands the gate
    opens and the output is token-identical."""
    prompt = list(range(100, 120))          # 3 pages
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)

    async def main():
        plane = MemoryPlane()
        SlowLastChunk.hold = asyncio.Event()
        decode, prefill, group, transfer = await _build_sharded_stack(
            plane, transfer_cls=SlowLastChunk)
        await decode.start()
        await prefill.start()
        try:
            frames = []
            gen = decode.generate(pre_request("ro", prompt).model_dump(
                exclude_none=True), Context("ro"))
            # first frame: the early-emitted first token, before the
            # straggler chunk has landed
            first = await asyncio.wait_for(gen.__anext__(), 60)
            frames.append(first)
            assert first.get("token_ids"), first
            assert decode.early_first_emits == 1
            # pull the next frame concurrently so the generator arms
            # the gate, then verify the straggler holds it
            nxt = asyncio.create_task(gen.__anext__())
            sch = decode.engine.scheduler
            deadline = asyncio.get_event_loop().time() + 30
            while "ro" not in sch.overlap_gates and not nxt.done():
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.01)
            assert not nxt.done(), "decode frame arrived while the " \
                "straggler stream still held a slice of the last page"
            seq = sch.remote.get("ro")
            assert seq is not None
            # wait for the healthy stream to finish and the straggler
            # to park one chunk short: min over per-stream frontiers ->
            # the request-wide frontier is 2 of 3 and the gate holds
            def stream_state():
                return group.stream_frontiers("ro", seq.epoch)
            while not (stream_state().get("dec-0/h0#0", 0) == 3
                       and stream_state().get("dec-0/h1#1", 0) == 2):
                assert asyncio.get_event_loop().time() < deadline, \
                    stream_state()
                assert not nxt.done()
                await asyncio.sleep(0.01)
            assert group.committed_frontier("ro", seq.epoch) == 2
            assert not nxt.done(), "decode started below the min frontier"
            gated = await decode.submit(
                lambda eng: eng.scheduler.poll_overlap_gates())
            assert gated == 0, \
                "gate opened before the straggler stream committed"
            SlowLastChunk.hold.set()
            frames.append(await asyncio.wait_for(nxt, 60))
            async for frame in gen:
                frames.append(frame)
        finally:
            await _teardown(decode, prefill, group, transfer)
        toks = [t for f in frames for t in f.get("token_ids", ())]
        reasons = [f.get("finish_reason") for f in frames
                   if f.get("finish_reason")]
        return toks, reasons, decode.engine.scheduler.overlap_activations

    toks, reasons, activations = asyncio.run(main())
    assert toks == expect and reasons == ["length"]
    assert activations == 1


# -- units: layout, plan, frontier aggregation, cost model ---------------------

def test_kv_shard_layout_shapes():
    assert kv_shard_layout(4, 4, tp=2) == [((1, 0, 2),), ((1, 2, 2),)]
    assert kv_shard_layout(4, 4, tp=1) == [((1, 0, 4),)]
    assert kv_shard_layout(4, 4, tp=2, pp=2) == [
        ((0, 0, 2), (1, 0, 2)), ((0, 0, 2), (1, 2, 2)),
        ((0, 2, 2), (1, 0, 2)), ((0, 2, 2), (1, 2, 2))]
    assert kv_shard_layout(2, 2, n_streams=2) == [((1, 0, 1),),
                                                  ((1, 1, 1),)]
    with pytest.raises(ValueError, match="divide"):
        kv_shard_layout(2, 2, n_streams=3)
    with pytest.raises(ValueError, match="pp"):
        kv_shard_layout(4, 4, pp=2, n_streams=2)


def test_group_frontier_is_min_over_streams():
    """Unit: the group facade answers min(over endpoints' min(over
    streams)) — the single number salvage/overlap/resume consume."""
    from dynamo_tpu.disagg.remote_transfer import KvTransferServer

    class W:     # bare worker stand-in
        pass

    w = W()
    g = object.__new__(ShardedKvTransferGroup)
    g.worker, g.engine_id, g.n_streams = w, "e", 3
    s0 = KvTransferServer(w, "e", host_label="h0",
                          streams={0: ((1, 0, 1),), 2: ((1, 2, 1),)},
                          attach=False)
    s1 = KvTransferServer(w, "e", host_label="h1",
                          streams={1: ((1, 1, 1),)}, attach=False)
    g.servers = [s0, s1]
    assert g.committed_frontier("r", 7) == 0
    s0._session("r", 7, total_pages=5, stream=0).committed_pages = 5
    s1._session("r", 7, total_pages=5, stream=1).committed_pages = 3
    assert g.committed_frontier("r", 7) == 0   # stream 2 never opened
    s0._session("r", 7, total_pages=5, stream=2).committed_pages = 4
    assert g.committed_frontier("r", 7) == 3   # min(5, 3, 4)
    assert g.stream_frontiers("r", 7) == {
        "e/h0#0": 5, "e/h1#1": 3, "e/h0#2": 4}
    # a different epoch sees nothing
    assert g.committed_frontier("r", 8) == 0
    g.forget("r")
    assert g.committed_frontier("r", 7) == 0


def test_cost_model_prices_parallel_stream_groups():
    """set_group: bytes split per member, wall = slowest member share,
    aggregate bandwidth = sum of member EWMAs, backlog per destination
    host, cold only when every member is cold."""
    from dynamo_tpu.observability.fleet import TransferCostModel
    m = TransferCostModel()
    m.set_group("eng", ["eng/h0", "eng/h1"])
    # both cold: median prior per member, still cold
    est = m.estimate("eng", 1 << 20)
    assert est.cold
    m.observe("eng/h0", 100 * 1024 * 1024, 1.0)   # 100 MiB/s
    m.observe("eng/h1", 50 * 1024 * 1024, 1.0)    # 50 MiB/s (straggler)
    est = m.estimate("eng", 100 * 1024 * 1024)
    assert not est.cold
    # 50 MiB share over the 50 MiB/s member gates the wall clock
    assert est.seconds == pytest.approx(1.0, rel=0.05)
    assert est.bytes_per_s == pytest.approx(150 * 1024 * 1024, rel=0.05)
    # single-link estimate for comparison: the group is ~2x faster
    m2 = TransferCostModel()
    m2.observe("solo", 50 * 1024 * 1024, 1.0)
    assert m2.estimate("solo", 100 * 1024 * 1024).seconds \
        == pytest.approx(2.0, rel=0.05)
    # backlog per destination host: queue_s = worst member drain
    m.note_inflight("eng/h1", 50 * 1024 * 1024)
    assert m.queue_s("eng") == pytest.approx(1.0, rel=0.05)
    m.note_done("eng/h1", 50 * 1024 * 1024)
    assert m.queue_s("eng") == 0.0
    # degenerate groups dissolve
    m.set_group("eng", ["eng/h0"])
    assert m.group_members("eng") is None


def test_trace_explain_stream_table_and_fleet_top_straggler():
    """Satellite surfaces: trace_explain --summary tabulates per-stream
    totals + the min-frontier stall naming the straggler; fleet_top
    flags the min-frontier straggler stream. Old artifacts (no stream
    spans / no xfer_streams) render unchanged."""
    import importlib.util as iu
    import os

    def load(mod, rel):
        spec = iu.spec_from_file_location(
            mod, os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), rel))
        m = iu.module_from_spec(spec)
        spec.loader.exec_module(m)
        return m

    te = load("_te", "tools/trace_explain.py")
    spans = [
        {"trace_id": "t1", "name": "kv.transfer.stream", "ts": 0.0,
         "dur": 0.10, "attrs": {"request_id": "r", "engine_id": "e",
                                "host": "h0", "stream": 0,
                                "bytes": 100, "resumes": 0}},
        {"trace_id": "t1", "name": "kv.transfer.stream", "ts": 0.0,
         "dur": 0.25, "attrs": {"request_id": "r", "engine_id": "e",
                                "host": "h1", "stream": 1,
                                "bytes": 100, "resumes": 1}},
    ]
    table = "\n".join(te.stream_frontier_table(spans))
    assert "e/h0#0" in table and "e/h1#1" in table
    assert "min-frontier stall" in table and "150.00 ms" in table
    # the straggler column marks the slowest stream of the transfer
    h1_row = [ln for ln in table.splitlines() if "e/h1#1" in ln][0]
    assert h1_row.rstrip().endswith("1")
    assert te.stream_frontier_table([]) == []

    ft = load("_ft", "tools/fleet_top.py")
    out = ft.render_summary({
        "ts": 0, "scrapes": 1, "workers_seen": 0, "fleet": {},
        "serving": {}, "cp": {}, "roles": {}, "qos": {}, "links": {},
        "xfer_streams": {
            "e/h0#0": {"bytes": 10, "pages": 4, "resumes": 0,
                       "frontier": 4},
            "e/h1#1": {"bytes": 10, "pages": 4, "resumes": 1,
                       "frontier": 2},
        }})
    assert "kv-transfer streams" in out
    straggler_lines = [ln for ln in out.splitlines()
                       if "min-frontier straggler" in ln]
    assert len(straggler_lines) == 1 and "e/h1#1" in straggler_lines[0]


def test_stream_plan_orders_and_fractions():
    from dynamo_tpu.disagg.remote_transfer import (
        RemoteTransferBackend, _StreamCtx,
    )
    plan = RemoteTransferBackend._stream_plan(
        RemoteTransferBackend.__new__(RemoteTransferBackend), "e", {
            "h1": {"streams": [{"stream": 1,
                                "slices": [[1, 1, 1]]}]},
            "h0": {"streams": [{"stream": 0,
                                "slices": [[1, 0, 1]]}]},
        })
    assert [c.stream for c in plan] == [0, 1]
    assert plan[0].conn_key == "e/h0#0" and plan[1].link == "e/h1"
    shape = (2, 2, 4, 8, 4)
    assert plan[0].fraction(shape) == pytest.approx(0.5)
    legacy = _StreamCtx("e")
    assert legacy.conn_key == "e" and legacy.fraction(shape) == 1.0
