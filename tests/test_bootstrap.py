"""Multi-process mesh bootstrap test (jax.distributed, VERDICT item 3).

Two OS processes join one coordinator and run the FULL engine generate over
a single global (dp, tp) mesh — XLA collectives cross the process boundary.
Both SPMD processes must emit identical tokens.
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.xfail(
    reason="this jax build (0.4.37) refuses multi-process computations on "
           "the CPU backend ('Multiprocess computations aren't implemented "
           "on the CPU backend'); the 2-process mesh path is validated on "
           "real TPU by the MULTICHIP dryruns (MULTICHIP_r05: 2-process "
           "dp=2 tp=4). Tracking note: TRIAGE_r06.md. run=False: the "
           "doomed children still burn ~60s of the tier-1 budget on "
           "engine builds before hitting the backend error",
    strict=False, run=False)
def test_two_process_engine_mesh_parity():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # children force their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.parallel.bootstrap",
             "--selftest-child", "--coordinator", coord,
             "--num-processes", "2", "--process-id", str(i),
             "--local-devices", "2"],
            stdout=subprocess.PIPE, env=env, text=True, cwd=REPO)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, (p.returncode, out)
    lines = [next(ln for ln in o.splitlines() if ln.startswith("MPDRY"))
             for o in outs]
    toks = {ln.split("tokens=")[1] for ln in lines}
    assert len(toks) == 1, lines
    assert "devices=4" in lines[0]
