"""Native C++ SPM-BPE encoder: exact parity with the Python algorithm.

The native encoder (native/spm_bpe.cpp) must be a bit-for-bit twin of
llm/gguf._spm_encode — same score-driven merge order, leftmost tie-breaks,
<0xXX> byte fallback, unk handling — because GGUFTokenizer silently prefers
it when the toolchain is present. Fuzzing over random vocabs and random
texts (including multi-byte UTF-8 and characters absent from the vocab) is
the strongest pin available.
"""
import random
import string

import pytest

from dynamo_tpu.llm.gguf import _spm_encode, _spm_prepare
from dynamo_tpu.native.spm import available, make_encoder

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable")

SPACE = "▁"


def build_vocab(rng, n_merge_tokens=60):
    """Random SPM-style vocab: specials, byte tokens, chars, merged pieces
    with random scores (ties included deliberately: int scores collide)."""
    toks = ["<unk>", "<s>", "</s>"]
    toks += [f"<0x{b:02X}>" for b in range(256)]
    chars = list("abcdefg") + [SPACE, "é", "λ", "中"]
    toks += chars
    pieces = set(chars)
    for _ in range(n_merge_tokens):
        a, b = rng.choice(sorted(pieces)), rng.choice(sorted(pieces))
        if len(a) + len(b) <= 6:
            pieces.add(a + b)
            toks.append(a + b)
    # duplicate a token on purpose: first-id-wins must hold on both sides
    toks.append(chars[0])
    scores = [float(rng.randint(-8, 8)) for _ in toks]
    byte_ids = {b: 3 + b for b in range(256)}
    ids = {}
    for i, t in enumerate(toks):
        ids.setdefault(t, i)
    return toks, scores, byte_ids, ids


def random_text(rng, n):
    alphabet = list("abcdefg  ") + ["é", "λ", "中", "Z", "!", "\n"]
    return "".join(rng.choice(alphabet) for _ in range(n))


def test_native_matches_python_fuzz():
    rng = random.Random(7)
    for trial in range(30):
        toks, scores, byte_ids, ids = build_vocab(rng)
        enc = make_encoder(toks, scores, byte_ids, 0)
        assert enc is not None
        for _ in range(20):
            text = random_text(rng, rng.randint(0, 40))
            want = _spm_encode(text, ids, scores, byte_ids, 0, SPACE, True)
            got = enc.encode(_spm_prepare(text, SPACE, True))
            assert got == want, (trial, text)


def test_native_empty_and_unk():
    toks = ["<unk>", "a", "b", "ab"]
    scores = [0.0, 0.0, 0.0, 5.0]
    enc = make_encoder(toks, scores, {}, 0)
    assert enc.encode("") == []
    assert enc.encode("ab") == [3]
    # no byte tokens, char absent from vocab -> unk
    assert enc.encode("zz") == [0, 0]


def test_gguf_tokenizer_uses_native(tmp_path):
    """GGUFTokenizer picks the native encoder and produces the same ids
    the Python path does on the standard tiny SPM vocab."""
    from dynamo_tpu.llm.gguf import GGUFFile, GGUFTokenizer
    from tests.test_gguf import make_tiny_gguf

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    tok = GGUFTokenizer(GGUFFile(path))
    assert tok._native is not None
    ids = tok.encode("hello world the")
    want = _spm_encode("hello world the", tok._ids, tok._scores,
                       tok._byte_ids, tok.unk_token_id, tok.SPACE,
                       tok._add_prefix)
    assert ids == want
    assert tok.decode(ids) == "hello world the"
