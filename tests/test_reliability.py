"""Reliability layer unit tests (frontend/reliability.py).

The chaos harness (tests/test_chaos.py) proves the end-to-end zero-drop
property on real engines; these tests pin the mechanisms one at a time on
fast fakes: circuit breaker state machine (no sleeps > ~1s), mid-stream
migration exactness over echo workers, bounded dispatch retries, deadline
propagation and enforcement, admission-control shedding, and the leased
prefill-queue redelivery primitives.
"""
import asyncio

import pytest

from dynamo_tpu.frontend.reliability import (
    AdmissionControl, AdmissionShed, CircuitBreaker, ReliabilityMetrics,
    ReliabilityPolicy, ReliableClient,
)
from dynamo_tpu.llm.worker import EchoTokenEngine, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane


def run(coro):
    return asyncio.run(coro)


def pre_request(rid, prompt, max_tokens):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


# -- circuit breaker ----------------------------------------------------------


def test_breaker_opens_after_n_failures_and_readmits_after_probe():
    """Acceptance: a worker failing N consecutive dispatches is ejected;
    successful probes re-admit it. Simulated clock — no sleeps."""
    clock = [0.0]
    metrics = ReliabilityMetrics()
    br = CircuitBreaker(failure_threshold=3, cooldown_s=5.0,
                        probe_successes=2, metrics=metrics,
                        clock=lambda: clock[0])
    assert br.allow("w")
    br.record_failure("w")
    br.record_failure("w")
    assert br.allow("w")           # still below threshold
    br.record_failure("w")
    assert not br.allow("w")       # open: ejected
    assert br.blocked() == {"w"}
    assert metrics.breaker_opens.get() == 1

    clock[0] = 4.9
    assert not br.allow("w")       # cooldown not elapsed
    clock[0] = 5.1
    assert br.allow("w")           # half-open: one probe admitted
    br.on_dispatch("w")
    assert not br.allow("w")       # probe in flight: no pile-on
    br.record_failure("w")         # probe failed: re-open
    assert not br.allow("w")
    assert metrics.breaker_opens.get() == 1  # re-open is not a new open

    clock[0] = 10.2
    assert br.allow("w")
    br.on_dispatch("w")
    br.record_success("w")         # probe 1/2
    assert br.allow("w")
    br.on_dispatch("w")
    br.record_success("w")         # probe 2/2: closed
    assert br.allow("w")
    assert br.blocked() == set()
    assert metrics.breaker_closes.get() == 1
    # healthy instance is unaffected throughout
    assert br.allow("other")


def test_breaker_abandoned_probe_is_released_not_leaked():
    """An attempt abandoned with no outcome (caller cancel, request
    deadline) must free the half-open probe slot, or the instance stays
    ejected forever."""
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                        clock=lambda: clock[0])
    br.record_failure("w")
    clock[0] = 1.5
    assert br.allow("w")
    br.on_dispatch("w")
    assert not br.allow("w")
    br.release_probe("w")          # abandoned, no outcome
    assert br.allow("w")           # slot free for the next probe
    br.on_dispatch("w")
    br.record_success("w")
    assert br.blocked() == set()


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0)
    for _ in range(5):
        br.record_failure("w")
        br.record_success("w")
    assert br.allow("w")           # never opened: failures not consecutive


# -- latency-tripped SLOW state (fail-slow plane) ------------------------------


def test_slow_state_reduces_share_but_never_ejects():
    """SLOW is not OPEN: a latency-tripped instance keeps dispatching at
    slow_share — that residual traffic IS the recovery probe stream."""
    clock = [0.0]
    br = CircuitBreaker(slow_share=0.25, reearn_s=10.0,
                        clock=lambda: clock[0])
    assert br.dispatch_weight("w") == 1.0
    br.trip_slow("w")
    assert br.is_slow("w")
    assert br.state_of("w") == "slow"
    assert br.dispatch_weight("w") == 0.25
    assert br.allow("w")               # never ejected
    assert br.blocked() == set()


def test_slow_clear_reearns_traffic_linearly():
    clock = [0.0]
    br = CircuitBreaker(slow_share=0.25, reearn_s=10.0,
                        clock=lambda: clock[0])
    br.trip_slow("w")
    br.clear_slow("w")
    assert not br.is_slow("w")
    # ramp: slow_share at t=0 -> 1.0 at reearn_s, linear in between
    assert br.dispatch_weight("w") == pytest.approx(0.25)
    clock[0] = 5.0
    assert br.dispatch_weight("w") == pytest.approx(0.625)
    clock[0] = 10.5
    assert br.dispatch_weight("w") == 1.0
    # and the ramp state is cleaned up, not recomputed forever
    assert br.dispatch_weight("w") == 1.0


def test_slow_is_orthogonal_to_error_states():
    """An instance can be SLOW and OPEN at once; OPEN (the stronger
    claim) wins state_of and the dispatch gate, and clearing the error
    state leaves the SLOW plane intact."""
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        probe_successes=1, clock=lambda: clock[0])
    br.trip_slow("w")
    br.record_failure("w")
    assert br.state_of("w") == "open"
    assert not br.allow("w")           # error ejection trumps SLOW
    clock[0] = 5.1
    br.on_dispatch("w")
    br.record_success("w")             # probe closes the error state
    assert br.state_of("w") == "slow"  # latency plane still remembers
    assert br.dispatch_weight("w") == br.slow_share
    br.clear_slow("w")
    clock[0] = 100.0
    assert br.state_of("w") == "closed"


def test_slow_trip_is_idempotent_and_forget_clears_it():
    br = CircuitBreaker()
    br.trip_slow("w")
    br.trip_slow("w")                  # no double-trip bookkeeping
    assert br.is_slow("w")
    br.forget("w")
    assert not br.is_slow("w")
    assert br.dispatch_weight("w") == 1.0
    # clear_slow on an unknown instance is a no-op, not a KeyError
    br.clear_slow("ghost")


def test_watch_delete_evicts_breaker_and_health_three_generations():
    """Regression: a worker name reused across 3 register/death cycles
    must start each generation with a clean breaker AND clean health
    evidence — without the watch-delete hook, generation 2 inherits
    generation 1's open breaker or SLOW flag and is ejected at birth."""
    from dynamo_tpu.runtime.health import HealthScorer

    class StubClient:
        def __init__(self):
            self.listeners = []

        def add_listener(self, fn):
            self.listeners.append(fn)

        def instance_ids(self):
            return []

    stub = StubClient()
    health = HealthScorer(min_evidence=3, enter_evals=1, exit_evals=1,
                          clock=lambda: 0.0)
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1e9)
    rel = ReliableClient(stub, ReliabilityPolicy(), breaker=br,
                         health=health)
    assert stub.listeners == [rel._on_instance_event]

    for generation in range(3):
        # the generation accumulates damning evidence on "w0"...
        br.record_failure("w0")
        br.record_failure("w0")
        assert not br.allow("w0"), generation
        for _ in range(4):
            for w, v in (("w0", 9.0), ("a", 0.05), ("b", 0.05),
                         ("c", 0.05)):
                health.observe(w, v)
        health.evaluate(float(generation))
        assert health.is_slow("w0"), generation
        # ...then dies; the watch pump delivers the delete
        rel._on_instance_event("delete", "w0", None)
        assert br.allow("w0"), generation          # clean breaker
        assert br.state_of("w0") == "closed"
        assert not health.is_slow("w0"), generation
        assert health.evidence("w0") == 0, generation


# -- admission control (load shedding) ----------------------------------------


def test_admission_caps_and_sheds():
    async def main():
        metrics = ReliabilityMetrics()
        adm = AdmissionControl(max_inflight=1, max_queued=1,
                               queue_timeout_s=5.0, retry_after_s=7,
                               metrics=metrics)
        await adm.acquire()                       # slot 1: runs
        waiter = asyncio.create_task(adm.acquire())   # queued
        await asyncio.sleep(0.01)
        with pytest.raises(AdmissionShed) as exc:     # queue full: shed
            await adm.acquire()
        assert exc.value.retry_after_s == 7
        assert metrics.shed_requests.get() == 1
        adm.release()                             # slot transfers to waiter
        await asyncio.wait_for(waiter, 1.0)
        adm.release()
        assert adm.active == 0

    run(main())


def test_admission_queue_timeout_sheds():
    async def main():
        adm = AdmissionControl(max_inflight=1, max_queued=4,
                               queue_timeout_s=0.05)
        await adm.acquire()
        with pytest.raises(AdmissionShed):
            await adm.acquire()     # waits 0.05s, never released: shed
        adm.release()
        assert adm.active == 0

    run(main())


# -- migration / retry over real wire (echo workers) --------------------------


class FlakyEngine(EchoTokenEngine):
    """Streams `hang_after` tokens then hangs forever — the shape of a
    worker whose engine died while its transport stayed up."""

    def __init__(self, hang_after=3):
        super().__init__()
        self.hang_after = hang_after

    async def generate(self, request, context):
        n = 0
        async for frame in super().generate(request, context):
            yield frame
            n += len(frame.get("token_ids", ()))
            if n >= self.hang_after:
                await asyncio.Event().wait()


async def _serving_pair(plane, flaky_after=3):
    w1 = await DistributedRuntime.create_local(plane, "flaky")
    await serve_llm_worker(w1, "ns", "backend", FlakyEngine(flaky_after))
    w2 = await DistributedRuntime.create_local(plane, "good")
    await serve_llm_worker(w2, "ns", "backend", EchoTokenEngine())
    crt = await DistributedRuntime.create_local(plane, "cl")
    client = crt.namespace("ns").component("backend").endpoint(
        "generate").client()
    await client.start()
    await client.wait_for_instances()
    return [w1, w2, crt], client


def test_mid_stream_migration_no_dup_no_gap():
    """A stream stalling mid-flight resumes on the other instance with the
    committed prefix: the client sees every token exactly once."""
    async def main():
        rts, client = await _serving_pair(MemoryPlane())
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            ReliabilityPolicy(stall_timeout_s=0.2, max_attempts=6,
                              backoff_base_s=0.01),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics)
        prompt = list(range(10, 22))
        try:
            for i in range(4):   # round robin is forced through both
                toks, finishes = [], []
                async for frame in rel.generate(
                        pre_request(f"m{i}", prompt, 12), Context(f"m{i}")):
                    toks.extend(frame.get("token_ids", ()))
                    if frame.get("finish_reason"):
                        finishes.append(frame["finish_reason"])
                assert toks == prompt, (i, toks)
                assert finishes == ["length"], finishes
        finally:
            for rt in rts:
                await rt.shutdown()
        return metrics.snapshot()

    snap = run(main())
    assert snap["migrations"] >= 1
    assert snap["stall_fires"] >= 1
    assert snap["breaker_opens"] == 1   # flaky ejected after first stall


def test_dispatch_retry_exhaustion_yields_error_frame():
    """With no serving instance, the layer retries with backoff and ends
    the stream with an ERROR frame — never an exception."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        served = await serve_llm_worker(wrt, "ns", "backend",
                                        EchoTokenEngine())
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        await served.shutdown()   # gone before the first dispatch
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client, ReliabilityPolicy(max_attempts=3, backoff_base_s=0.01,
                                      dispatch_timeout_s=0.5,
                                      instance_wait_s=0.2),
            metrics=metrics)
        frames = []
        async for frame in rel.generate(
                pre_request("x", [1, 2, 3], 3), Context("x")):
            frames.append(frame)
        await crt.shutdown()
        await wrt.shutdown()
        return frames, metrics.snapshot()

    frames, snap = run(main())
    assert len(frames) == 1
    assert frames[0]["finish_reason"] == "error"
    assert snap["retries"] == 2   # attempts 2 and 3


def test_request_scoped_error_forwarded_not_retried():
    """A deterministic per-request rejection (ERROR frame with
    retryable=False, e.g. OOV prompt at engine admission) must be
    forwarded once — no retries, and no breaker damage to the healthy
    worker that correctly rejected it."""
    from dynamo_tpu.protocols.common import EngineOutput, FinishReason
    from dynamo_tpu.runtime.engine import FnEngine

    calls = {"n": 0}

    async def rejecting(request, context):
        calls["n"] += 1
        yield EngineOutput(finish_reason=FinishReason.ERROR, retryable=False,
                           text="token id 999 outside the model vocab"
                           ).model_dump(exclude_none=True)

    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        await serve_llm_worker(wrt, "ns", "backend", FnEngine(rejecting))
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        metrics = ReliabilityMetrics()
        breaker = CircuitBreaker(failure_threshold=1, metrics=metrics)
        rel = ReliableClient(client,
                             ReliabilityPolicy(backoff_base_s=0.01),
                             breaker=breaker, metrics=metrics)
        frames = [f async for f in rel.generate(
            pre_request("oov", [1, 2, 3], 3), Context("oov"))]
        await crt.shutdown()
        await wrt.shutdown()
        return frames, breaker.blocked(), metrics.snapshot()

    frames, blocked, snap = run(main())
    assert calls["n"] == 1                      # exactly one dispatch
    assert frames[-1]["finish_reason"] == "error"
    assert "vocab" in frames[-1]["text"]
    assert blocked == set()                     # worker not ejected
    assert snap["retries"] == 0 and snap["migrations"] == 0


def test_duplicate_in_flight_id_rejected_without_clobbering():
    """A second dispatch of a live request id is rejected with a
    non-retryable ERROR frame and the FIRST stream keeps its frames."""
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.llm.worker import NativeEngineWorker

    async def main():
        engine = NativeEngine(
            ModelConfig(dtype="float32", max_model_len=512),
            EngineConfig(page_size=8, num_pages=64, max_slots=4,
                         max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                         max_model_len=512), seed=0)
        worker = await NativeEngineWorker(engine).start()
        try:
            req = pre_request("dup", list(range(10, 26)), 4)
            first_toks, dup_frames = [], []

            async def first():
                async for f in worker.generate(req, Context("dup")):
                    first_toks.extend(f.get("token_ids", ()))
                    if f.get("finish_reason"):
                        return f["finish_reason"]

            t = asyncio.create_task(first())
            await asyncio.sleep(0.05)   # first stream is live
            async for f in worker.generate(req, Context("dup2")):
                dup_frames.append(f)
            reason = await asyncio.wait_for(t, 60)
        finally:
            await worker.stop()
        return first_toks, reason, dup_frames

    first_toks, reason, dup_frames = run(main())
    assert reason == "length" and len(first_toks) == 4   # survived intact
    assert dup_frames[-1]["finish_reason"] == "error"
    assert dup_frames[-1]["retryable"] is False
    assert "already in flight" in dup_frames[-1]["text"]


def test_deadline_propagates_and_fails_cleanly():
    """An armed Context deadline bounds the whole request: a wedged worker
    turns into an ERROR frame once the budget is spent, and the deadline
    crosses the wire to the worker's Context."""
    seen = {}

    class WedgedEngine(EchoTokenEngine):
        async def generate(self, request, context):
            seen["remaining"] = context.time_remaining()
            await asyncio.Event().wait()
            yield  # pragma: no cover

    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        await serve_llm_worker(wrt, "ns", "backend", WedgedEngine())
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client, ReliabilityPolicy(stall_timeout_s=10.0,
                                      request_deadline_s=0.4,
                                      backoff_base_s=0.01),
            metrics=metrics)
        ctx = Context("d1")
        frames = []
        t0 = asyncio.get_event_loop().time()
        async for frame in rel.generate(pre_request("d1", [1, 2, 3], 3),
                                        ctx):
            frames.append(frame)
        elapsed = asyncio.get_event_loop().time() - t0
        await crt.shutdown()
        await wrt.shutdown()
        return frames, elapsed, metrics.snapshot()

    frames, elapsed, snap = run(main())
    assert frames[-1]["finish_reason"] == "error"
    assert "deadline" in frames[-1]["text"]
    assert elapsed < 5.0          # the 10s stall timeout did NOT govern
    assert snap["deadline_exceeded"] == 1
    # the worker-side Context carried the (remaining) deadline
    assert seen["remaining"] is not None and 0 < seen["remaining"] <= 0.4


def test_caller_abort_mid_migration_stays_cancelled():
    """A client abort during a stall/migration window ends the stream with
    CANCELLED, not with a retry storm."""
    async def main():
        rts, client = await _serving_pair(MemoryPlane(), flaky_after=2)
        rel = ReliableClient(
            client,
            ReliabilityPolicy(stall_timeout_s=0.3, max_attempts=10,
                              backoff_base_s=0.2),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0))
        ctx = Context("a1")
        prompt = list(range(5, 17))
        toks, finishes = [], []
        try:
            async for frame in rel.generate(
                    pre_request("a1", prompt, 12), ctx):
                toks.extend(frame.get("token_ids", ()))
                if frame.get("finish_reason"):
                    finishes.append(frame["finish_reason"])
                if len(toks) == 2:
                    ctx.stop_generating()
        finally:
            for rt in rts:
                await rt.shutdown()
        return toks, finishes

    toks, finishes = run(main())
    assert toks[:2] == [5, 6]
    assert finishes[-1] == "cancelled"


# -- leased work queue (durability primitive) ---------------------------------


def test_queue_lease_redelivery_and_ack():
    async def main():
        plane = MemoryPlane()
        mq = plane.messaging
        await mq.queue_push("q", b"item")
        got = await mq.queue_pop_leased("q", timeout=0.2, lease_s=0.1)
        assert got is not None and got[0] == b"item"
        assert await mq.queue_depth("q") == 0
        # lease expires unacked -> redelivered
        await asyncio.sleep(0.15)
        got2 = await mq.queue_pop_leased("q", timeout=1.0, lease_s=5.0)
        assert got2 is not None and got2[0] == b"item"
        assert mq.redeliveries == 1
        # ack settles it for good
        await mq.queue_ack("q", got2[1])
        await asyncio.sleep(0.02)
        assert await mq.queue_pop_leased("q", timeout=0.05) is None

    run(main())


def test_queue_poison_item_dropped_after_max_redeliveries():
    async def main():
        plane = MemoryPlane()
        mq = plane.messaging
        mq.MAX_REDELIVERIES = 2
        await mq.queue_push("q", b"poison")
        for _ in range(3):   # initial delivery + 2 redeliveries
            got = await mq.queue_pop_leased("q", timeout=0.5, lease_s=0.01)
            assert got is not None
            await asyncio.sleep(0.02)   # let the lease lapse, never ack
        assert await mq.queue_pop_leased("q", timeout=0.05) is None
        assert mq.redeliveries == 2

    run(main())


# -- hedged dispatch (fail-slow plane) -----------------------------------------


class SlowFirstFrameEngine(EchoTokenEngine):
    """Healthy but laggy: every stream's first frame is delayed by
    `first_frame_s` — the shape of a gray-failed worker (alive, correct,
    slow), and exactly what the hedge window exists to dodge."""

    def __init__(self, first_frame_s=0.5):
        super().__init__()
        self.first_frame_s = first_frame_s

    async def generate(self, request, context):
        await asyncio.sleep(self.first_frame_s)
        async for frame in super().generate(request, context):
            yield frame


async def _hedge_fleet(plane, engines):
    """Serve `engines` as named instances; return (runtimes, client)."""
    rts = []
    for name, engine in engines:
        rt = await DistributedRuntime.create_local(plane, name)
        await serve_llm_worker(rt, "ns", "backend", engine)
        rts.append(rt)
    crt = await DistributedRuntime.create_local(plane, "cl")
    client = crt.namespace("ns").component("backend").endpoint(
        "generate").client()
    await client.start()
    await client.wait_for_instances()
    for _ in range(200):
        if len(client.instances) >= len(engines):
            break
        await asyncio.sleep(0.02)
    assert len(client.instances) == len(engines), client.instances
    rts.append(crt)
    return rts, client


def _hedge_policy(**kw):
    kw.setdefault("hedge_enabled", True)
    kw.setdefault("hedge_min_delay_s", 0.0)
    kw.setdefault("hedge_max_delay_s", 0.05)
    kw.setdefault("hedge_budget_frac", 1.0)
    kw.setdefault("hedge_burst", 16)
    kw.setdefault("stall_timeout_s", 5.0)
    kw.setdefault("backoff_base_s", 0.01)
    return ReliabilityPolicy(**kw)


def test_hedge_first_frame_wins_and_loser_is_cancelled():
    """Two laggy workers, zero hedge delay: every request races a hedge.
    First frame wins, the loser is cancelled pre-commit, and the client
    stream is token-identical to an unhedged echo either way."""
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    async def main():
        rts, client = await _hedge_fleet(
            MemoryPlane(), [("w1", SlowFirstFrameEngine(0.2)),
                            ("w2", SlowFirstFrameEngine(0.2))])
        HEDGE_STATS.reset()
        rel = ReliableClient(client, _hedge_policy(),
                             health=HealthScorer())
        prompt = list(range(40, 50))
        try:
            for i in range(3):
                toks = []
                async for frame in rel.generate(
                        pre_request(f"h{i}", prompt, 10), Context(f"h{i}")):
                    assert frame.get("finish_reason") != "error", frame
                    toks.extend(frame.get("token_ids", ()))
                assert toks == prompt, (i, toks)
        finally:
            for rt in rts:
                await rt.shutdown()
        return HEDGE_STATS.snapshot()

    snap = run(main())
    assert snap["fired"] == 3, snap
    # every race settled exactly once: a win or a loss, never both/neither
    assert snap["wins"] + snap["losses"] == snap["fired"], snap
    assert snap["fired_by_class"] == {"": 3}, snap


def test_hedge_no_candidate_on_single_instance_fleet():
    """One instance: the hedge window fires but there is no second
    choice — counted, not crashed, and the stream completes."""
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    async def main():
        rts, client = await _hedge_fleet(
            MemoryPlane(), [("w1", SlowFirstFrameEngine(0.2))])
        HEDGE_STATS.reset()
        rel = ReliableClient(client, _hedge_policy(),
                             health=HealthScorer())
        toks = []
        try:
            async for frame in rel.generate(
                    pre_request("h", [5, 6, 7], 3), Context("h")):
                toks.extend(frame.get("token_ids", ()))
        finally:
            for rt in rts:
                await rt.shutdown()
        return toks, HEDGE_STATS.snapshot()

    toks, snap = run(main())
    assert toks == [5, 6, 7]
    assert snap["no_candidate"] == 1, snap
    assert snap["fired"] == 0, snap


def test_hedge_budget_denied_counts_and_serves():
    """Budget exhausted: the hedge is refused (counted), the primary
    serves alone, and nothing errors — a sick fleet can't melt itself
    with duplicate work."""
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    async def main():
        rts, client = await _hedge_fleet(
            MemoryPlane(), [("w1", SlowFirstFrameEngine(0.2)),
                            ("w2", SlowFirstFrameEngine(0.2))])
        HEDGE_STATS.reset()
        rel = ReliableClient(
            client, _hedge_policy(hedge_budget_frac=0.0, hedge_burst=0),
            health=HealthScorer())
        toks = []
        try:
            async for frame in rel.generate(
                    pre_request("h", [5, 6, 7], 3), Context("h")):
                toks.extend(frame.get("token_ids", ()))
        finally:
            for rt in rts:
                await rt.shutdown()
        return toks, HEDGE_STATS.snapshot()

    toks, snap = run(main())
    assert toks == [5, 6, 7]
    assert snap["budget_denied"] == 1, snap
    assert snap["fired"] == 0, snap


def test_hedge_suppressed_once_tokens_commit():
    """The pre-commit exactness guard: a migrated (resumed) attempt
    carries committed tokens, so its hedge window never opens — counted
    as suppressed_commit, and the resumed stream stays token-exact."""
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    async def main():
        rts, client = await _serving_pair(MemoryPlane())
        HEDGE_STATS.reset()
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            # hedge windows are armed but the delay is far beyond the
            # stall timeout: no race ever fires, isolating the guard
            _hedge_policy(hedge_min_delay_s=30.0, hedge_max_delay_s=30.0,
                          stall_timeout_s=0.2, max_attempts=6),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics, health=HealthScorer())
        prompt = list(range(10, 22))
        try:
            for i in range(4):   # round robin forces the flaky instance
                toks = []
                async for frame in rel.generate(
                        pre_request(f"s{i}", prompt, 12), Context(f"s{i}")):
                    toks.extend(frame.get("token_ids", ()))
                assert toks == prompt, (i, toks)
        finally:
            for rt in rts:
                await rt.shutdown()
        return metrics.snapshot(), HEDGE_STATS.snapshot()

    rsnap, hsnap = run(main())
    assert rsnap["migrations"] >= 1, rsnap
    assert hsnap["suppressed_commit"] >= 1, hsnap
    assert hsnap["fired"] == 0, hsnap
