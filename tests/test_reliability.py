"""Reliability layer unit tests (frontend/reliability.py).

The chaos harness (tests/test_chaos.py) proves the end-to-end zero-drop
property on real engines; these tests pin the mechanisms one at a time on
fast fakes: circuit breaker state machine (no sleeps > ~1s), mid-stream
migration exactness over echo workers, bounded dispatch retries, deadline
propagation and enforcement, admission-control shedding, and the leased
prefill-queue redelivery primitives.
"""
import asyncio

import pytest

from dynamo_tpu.frontend.reliability import (
    AdmissionControl, AdmissionShed, CircuitBreaker, ReliabilityMetrics,
    ReliabilityPolicy, ReliableClient,
)
from dynamo_tpu.llm.worker import EchoTokenEngine, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane


def run(coro):
    return asyncio.run(coro)


def pre_request(rid, prompt, max_tokens):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


# -- circuit breaker ----------------------------------------------------------


def test_breaker_opens_after_n_failures_and_readmits_after_probe():
    """Acceptance: a worker failing N consecutive dispatches is ejected;
    successful probes re-admit it. Simulated clock — no sleeps."""
    clock = [0.0]
    metrics = ReliabilityMetrics()
    br = CircuitBreaker(failure_threshold=3, cooldown_s=5.0,
                        probe_successes=2, metrics=metrics,
                        clock=lambda: clock[0])
    assert br.allow("w")
    br.record_failure("w")
    br.record_failure("w")
    assert br.allow("w")           # still below threshold
    br.record_failure("w")
    assert not br.allow("w")       # open: ejected
    assert br.blocked() == {"w"}
    assert metrics.breaker_opens.get() == 1

    clock[0] = 4.9
    assert not br.allow("w")       # cooldown not elapsed
    clock[0] = 5.1
    assert br.allow("w")           # half-open: one probe admitted
    br.on_dispatch("w")
    assert not br.allow("w")       # probe in flight: no pile-on
    br.record_failure("w")         # probe failed: re-open
    assert not br.allow("w")
    assert metrics.breaker_opens.get() == 1  # re-open is not a new open

    clock[0] = 10.2
    assert br.allow("w")
    br.on_dispatch("w")
    br.record_success("w")         # probe 1/2
    assert br.allow("w")
    br.on_dispatch("w")
    br.record_success("w")         # probe 2/2: closed
    assert br.allow("w")
    assert br.blocked() == set()
    assert metrics.breaker_closes.get() == 1
    # healthy instance is unaffected throughout
    assert br.allow("other")


def test_breaker_abandoned_probe_is_released_not_leaked():
    """An attempt abandoned with no outcome (caller cancel, request
    deadline) must free the half-open probe slot, or the instance stays
    ejected forever."""
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                        clock=lambda: clock[0])
    br.record_failure("w")
    clock[0] = 1.5
    assert br.allow("w")
    br.on_dispatch("w")
    assert not br.allow("w")
    br.release_probe("w")          # abandoned, no outcome
    assert br.allow("w")           # slot free for the next probe
    br.on_dispatch("w")
    br.record_success("w")
    assert br.blocked() == set()


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0)
    for _ in range(5):
        br.record_failure("w")
        br.record_success("w")
    assert br.allow("w")           # never opened: failures not consecutive


# -- admission control (load shedding) ----------------------------------------


def test_admission_caps_and_sheds():
    async def main():
        metrics = ReliabilityMetrics()
        adm = AdmissionControl(max_inflight=1, max_queued=1,
                               queue_timeout_s=5.0, retry_after_s=7,
                               metrics=metrics)
        await adm.acquire()                       # slot 1: runs
        waiter = asyncio.create_task(adm.acquire())   # queued
        await asyncio.sleep(0.01)
        with pytest.raises(AdmissionShed) as exc:     # queue full: shed
            await adm.acquire()
        assert exc.value.retry_after_s == 7
        assert metrics.shed_requests.get() == 1
        adm.release()                             # slot transfers to waiter
        await asyncio.wait_for(waiter, 1.0)
        adm.release()
        assert adm.active == 0

    run(main())


def test_admission_queue_timeout_sheds():
    async def main():
        adm = AdmissionControl(max_inflight=1, max_queued=4,
                               queue_timeout_s=0.05)
        await adm.acquire()
        with pytest.raises(AdmissionShed):
            await adm.acquire()     # waits 0.05s, never released: shed
        adm.release()
        assert adm.active == 0

    run(main())


# -- migration / retry over real wire (echo workers) --------------------------


class FlakyEngine(EchoTokenEngine):
    """Streams `hang_after` tokens then hangs forever — the shape of a
    worker whose engine died while its transport stayed up."""

    def __init__(self, hang_after=3):
        super().__init__()
        self.hang_after = hang_after

    async def generate(self, request, context):
        n = 0
        async for frame in super().generate(request, context):
            yield frame
            n += len(frame.get("token_ids", ()))
            if n >= self.hang_after:
                await asyncio.Event().wait()


async def _serving_pair(plane, flaky_after=3):
    w1 = await DistributedRuntime.create_local(plane, "flaky")
    await serve_llm_worker(w1, "ns", "backend", FlakyEngine(flaky_after))
    w2 = await DistributedRuntime.create_local(plane, "good")
    await serve_llm_worker(w2, "ns", "backend", EchoTokenEngine())
    crt = await DistributedRuntime.create_local(plane, "cl")
    client = crt.namespace("ns").component("backend").endpoint(
        "generate").client()
    await client.start()
    await client.wait_for_instances()
    return [w1, w2, crt], client


def test_mid_stream_migration_no_dup_no_gap():
    """A stream stalling mid-flight resumes on the other instance with the
    committed prefix: the client sees every token exactly once."""
    async def main():
        rts, client = await _serving_pair(MemoryPlane())
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            ReliabilityPolicy(stall_timeout_s=0.2, max_attempts=6,
                              backoff_base_s=0.01),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics)
        prompt = list(range(10, 22))
        try:
            for i in range(4):   # round robin is forced through both
                toks, finishes = [], []
                async for frame in rel.generate(
                        pre_request(f"m{i}", prompt, 12), Context(f"m{i}")):
                    toks.extend(frame.get("token_ids", ()))
                    if frame.get("finish_reason"):
                        finishes.append(frame["finish_reason"])
                assert toks == prompt, (i, toks)
                assert finishes == ["length"], finishes
        finally:
            for rt in rts:
                await rt.shutdown()
        return metrics.snapshot()

    snap = run(main())
    assert snap["migrations"] >= 1
    assert snap["stall_fires"] >= 1
    assert snap["breaker_opens"] == 1   # flaky ejected after first stall


def test_dispatch_retry_exhaustion_yields_error_frame():
    """With no serving instance, the layer retries with backoff and ends
    the stream with an ERROR frame — never an exception."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        served = await serve_llm_worker(wrt, "ns", "backend",
                                        EchoTokenEngine())
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        await served.shutdown()   # gone before the first dispatch
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client, ReliabilityPolicy(max_attempts=3, backoff_base_s=0.01,
                                      dispatch_timeout_s=0.5,
                                      instance_wait_s=0.2),
            metrics=metrics)
        frames = []
        async for frame in rel.generate(
                pre_request("x", [1, 2, 3], 3), Context("x")):
            frames.append(frame)
        await crt.shutdown()
        await wrt.shutdown()
        return frames, metrics.snapshot()

    frames, snap = run(main())
    assert len(frames) == 1
    assert frames[0]["finish_reason"] == "error"
    assert snap["retries"] == 2   # attempts 2 and 3


def test_request_scoped_error_forwarded_not_retried():
    """A deterministic per-request rejection (ERROR frame with
    retryable=False, e.g. OOV prompt at engine admission) must be
    forwarded once — no retries, and no breaker damage to the healthy
    worker that correctly rejected it."""
    from dynamo_tpu.protocols.common import EngineOutput, FinishReason
    from dynamo_tpu.runtime.engine import FnEngine

    calls = {"n": 0}

    async def rejecting(request, context):
        calls["n"] += 1
        yield EngineOutput(finish_reason=FinishReason.ERROR, retryable=False,
                           text="token id 999 outside the model vocab"
                           ).model_dump(exclude_none=True)

    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        await serve_llm_worker(wrt, "ns", "backend", FnEngine(rejecting))
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        metrics = ReliabilityMetrics()
        breaker = CircuitBreaker(failure_threshold=1, metrics=metrics)
        rel = ReliableClient(client,
                             ReliabilityPolicy(backoff_base_s=0.01),
                             breaker=breaker, metrics=metrics)
        frames = [f async for f in rel.generate(
            pre_request("oov", [1, 2, 3], 3), Context("oov"))]
        await crt.shutdown()
        await wrt.shutdown()
        return frames, breaker.blocked(), metrics.snapshot()

    frames, blocked, snap = run(main())
    assert calls["n"] == 1                      # exactly one dispatch
    assert frames[-1]["finish_reason"] == "error"
    assert "vocab" in frames[-1]["text"]
    assert blocked == set()                     # worker not ejected
    assert snap["retries"] == 0 and snap["migrations"] == 0


def test_duplicate_in_flight_id_rejected_without_clobbering():
    """A second dispatch of a live request id is rejected with a
    non-retryable ERROR frame and the FIRST stream keeps its frames."""
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.llm.worker import NativeEngineWorker

    async def main():
        engine = NativeEngine(
            ModelConfig(dtype="float32", max_model_len=512),
            EngineConfig(page_size=8, num_pages=64, max_slots=4,
                         max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                         max_model_len=512), seed=0)
        worker = await NativeEngineWorker(engine).start()
        try:
            req = pre_request("dup", list(range(10, 26)), 4)
            first_toks, dup_frames = [], []

            async def first():
                async for f in worker.generate(req, Context("dup")):
                    first_toks.extend(f.get("token_ids", ()))
                    if f.get("finish_reason"):
                        return f["finish_reason"]

            t = asyncio.create_task(first())
            await asyncio.sleep(0.05)   # first stream is live
            async for f in worker.generate(req, Context("dup2")):
                dup_frames.append(f)
            reason = await asyncio.wait_for(t, 60)
        finally:
            await worker.stop()
        return first_toks, reason, dup_frames

    first_toks, reason, dup_frames = run(main())
    assert reason == "length" and len(first_toks) == 4   # survived intact
    assert dup_frames[-1]["finish_reason"] == "error"
    assert dup_frames[-1]["retryable"] is False
    assert "already in flight" in dup_frames[-1]["text"]


def test_deadline_propagates_and_fails_cleanly():
    """An armed Context deadline bounds the whole request: a wedged worker
    turns into an ERROR frame once the budget is spent, and the deadline
    crosses the wire to the worker's Context."""
    seen = {}

    class WedgedEngine(EchoTokenEngine):
        async def generate(self, request, context):
            seen["remaining"] = context.time_remaining()
            await asyncio.Event().wait()
            yield  # pragma: no cover

    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w")
        await serve_llm_worker(wrt, "ns", "backend", WedgedEngine())
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client, ReliabilityPolicy(stall_timeout_s=10.0,
                                      request_deadline_s=0.4,
                                      backoff_base_s=0.01),
            metrics=metrics)
        ctx = Context("d1")
        frames = []
        t0 = asyncio.get_event_loop().time()
        async for frame in rel.generate(pre_request("d1", [1, 2, 3], 3),
                                        ctx):
            frames.append(frame)
        elapsed = asyncio.get_event_loop().time() - t0
        await crt.shutdown()
        await wrt.shutdown()
        return frames, elapsed, metrics.snapshot()

    frames, elapsed, snap = run(main())
    assert frames[-1]["finish_reason"] == "error"
    assert "deadline" in frames[-1]["text"]
    assert elapsed < 5.0          # the 10s stall timeout did NOT govern
    assert snap["deadline_exceeded"] == 1
    # the worker-side Context carried the (remaining) deadline
    assert seen["remaining"] is not None and 0 < seen["remaining"] <= 0.4


def test_caller_abort_mid_migration_stays_cancelled():
    """A client abort during a stall/migration window ends the stream with
    CANCELLED, not with a retry storm."""
    async def main():
        rts, client = await _serving_pair(MemoryPlane(), flaky_after=2)
        rel = ReliableClient(
            client,
            ReliabilityPolicy(stall_timeout_s=0.3, max_attempts=10,
                              backoff_base_s=0.2),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0))
        ctx = Context("a1")
        prompt = list(range(5, 17))
        toks, finishes = [], []
        try:
            async for frame in rel.generate(
                    pre_request("a1", prompt, 12), ctx):
                toks.extend(frame.get("token_ids", ()))
                if frame.get("finish_reason"):
                    finishes.append(frame["finish_reason"])
                if len(toks) == 2:
                    ctx.stop_generating()
        finally:
            for rt in rts:
                await rt.shutdown()
        return toks, finishes

    toks, finishes = run(main())
    assert toks[:2] == [5, 6]
    assert finishes[-1] == "cancelled"


# -- leased work queue (durability primitive) ---------------------------------


def test_queue_lease_redelivery_and_ack():
    async def main():
        plane = MemoryPlane()
        mq = plane.messaging
        await mq.queue_push("q", b"item")
        got = await mq.queue_pop_leased("q", timeout=0.2, lease_s=0.1)
        assert got is not None and got[0] == b"item"
        assert await mq.queue_depth("q") == 0
        # lease expires unacked -> redelivered
        await asyncio.sleep(0.15)
        got2 = await mq.queue_pop_leased("q", timeout=1.0, lease_s=5.0)
        assert got2 is not None and got2[0] == b"item"
        assert mq.redeliveries == 1
        # ack settles it for good
        await mq.queue_ack("q", got2[1])
        await asyncio.sleep(0.02)
        assert await mq.queue_pop_leased("q", timeout=0.05) is None

    run(main())


def test_queue_poison_item_dropped_after_max_redeliveries():
    async def main():
        plane = MemoryPlane()
        mq = plane.messaging
        mq.MAX_REDELIVERIES = 2
        await mq.queue_push("q", b"poison")
        for _ in range(3):   # initial delivery + 2 redeliveries
            got = await mq.queue_pop_leased("q", timeout=0.5, lease_s=0.01)
            assert got is not None
            await asyncio.sleep(0.02)   # let the lease lapse, never ack
        assert await mq.queue_pop_leased("q", timeout=0.05) is None
        assert mq.redeliveries == 2

    run(main())
