"""Native (C++) radix-tree indexer: build, load, and parity vs Python tree.

The native tree (dynamo_tpu/native/kv_indexer.cpp) mirrors the Python
RadixTree semantics (itself mirroring reference indexer.rs); parity is
checked over randomized event streams.
"""
import random

import pytest

from dynamo_tpu.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheRemoveData, KvCacheStoreData,
    KvCacheStoredBlockData, RouterEvent,
)

pytestmark = pytest.mark.skipif(
    not __import__("dynamo_tpu.native.radix", fromlist=["available"]
                   ).available(),
    reason="native toolchain unavailable")


def stored(worker, parent, blocks):
    return RouterEvent(worker_id=worker, event=KvCacheEvent(
        event_id=0, data=KvCacheStoreData(
            parent_hash=parent,
            blocks=[KvCacheStoredBlockData(block_hash=b, tokens_hash=t)
                    for b, t in blocks])))


def removed(worker, hashes):
    return RouterEvent(worker_id=worker, event=KvCacheEvent(
        event_id=0, data=KvCacheRemoveData(block_hashes=list(hashes))))


def test_native_matches_python_on_random_streams():
    from dynamo_tpu.native.radix import NativeRadixTree

    rng = random.Random(7)
    py, nat = RadixTree(), NativeRadixTree()
    workers = [f"w{i}" for i in range(5)]
    # per-worker chains: block_hash is unique per (worker, page);
    # tokens_hash is shared across workers (content-addressed)
    live: dict = {w: [] for w in workers}
    for step in range(400):
        w = rng.choice(workers)
        op = rng.random()
        if op < 0.55:
            # store a run extending the worker's chain or branching off root
            chain = live[w]
            if chain and rng.random() < 0.7:
                parent = chain[-1][0]
            else:
                parent = 0
            run = []
            for i in range(rng.randint(1, 4)):
                bh = rng.getrandbits(63) | 1
                th = (rng.getrandbits(16) | 1) if rng.random() < 0.5 \
                    else rng.choice([1, 2, 3, 4, 5])
                run.append((bh, th))
            ev = stored(w, parent if parent else None, run)
            py.apply_event(ev)
            nat.apply_event(ev)
            if parent == 0:
                live[w] = list(run)
            else:
                live[w].extend(run)
        elif op < 0.85 and live[w]:
            k = rng.randint(1, min(3, len(live[w])))
            victims = [bh for bh, _ in live[w][-k:]]
            ev = removed(w, victims)
            py.apply_event(ev)
            nat.apply_event(ev)
            live[w] = live[w][:-k]
        else:
            py.remove_worker(w)
            nat.remove_worker(w)
            live[w] = []
        if step % 20 == 0:
            q = [rng.choice([1, 2, 3, 4, 5]) for _ in range(rng.randint(1, 6))]
            assert nat.find_matches(q).scores == py.find_matches(q).scores
            assert nat.num_nodes() == py.num_nodes()
            for wk in workers:
                assert (nat.worker_block_count(wk)
                        == py.worker_block_count(wk))


def test_native_restore_under_new_block_hash_no_dangling():
    """Re-storing a page under a new block_hash then removing both hashes
    must not leave dangling table entries (C++ UAF regression)."""
    from dynamo_tpu.native.radix import NativeRadixTree

    py, nat = RadixTree(), NativeRadixTree()
    for t in (py, nat):
        t.apply_event(stored("w", None, [(11, 5)]))
        t.apply_event(stored("w", None, [(22, 5)]))   # same page, new bh
        t.apply_event(removed("w", [22]))             # prunes the node
        t.apply_event(removed("w", [11]))             # stale hash: no-op
        t.apply_event(stored("w", 11, [(33, 6)]))     # unknown parent: drop
        t.apply_event(stored("w", None, [(44, 7)]))
    assert nat.find_matches([5, 6, 7]).scores == py.find_matches(
        [5, 6, 7]).scores == {}
    assert nat.find_matches([7]).scores == py.find_matches(
        [7]).scores == {"w": 1}
    assert nat.num_nodes() == py.num_nodes() == 1


def test_kv_indexer_uses_native_tree():
    from dynamo_tpu.native.radix import NativeRadixTree

    idx = KvIndexer(block_size=4)
    assert isinstance(idx.tree, NativeRadixTree)
    # frequency tracking forces the Python tree
    idx2 = KvIndexer(block_size=4, expiration_duration_s=1.0)
    assert isinstance(idx2.tree, RadixTree)
    # events + token-level matching round-trip through the native path
    idx.apply_event(stored("w1", None, [(10, 101), (11, 102)]))
    res = idx.find_matches([101, 102, 103])
    assert res.scores == {"w1": 2}
    idx.remove_worker("w1")
    assert idx.find_matches([101]).scores == {}
