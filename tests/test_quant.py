"""Weight-only int8 quantized serving (ops/quant.py; VERDICT r4 weak #6).

The reference delegates quantized serving to its engines (AWQ/GPTQ via
vLLM/TRT-LLM, SURVEY.md §2.8); here `ModelConfig.quant="int8"` is a
first-class engine mode: dense projections + lm_head live in HBM as int8
with per-output-channel scales, dequantized inside the matmul producers.
"""
import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.models import llama
from dynamo_tpu.ops.quant import (
    is_quantized, quantize_int8, quantize_params, wmat,
)
from dynamo_tpu.parallel.mesh import make_mesh

CFG = ModelConfig(dtype="float32", quant="int8", max_model_len=256)
ECFG = EngineConfig(page_size=8, num_pages=64, max_slots=2,
                    max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                    max_model_len=256)


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(3)
    w = rng.standard_normal((4, 64, 96)).astype(np.float32)
    for xp in (np, jnp):
        qt = quantize_int8(w, xp=xp)
        assert np.asarray(qt["q"]).dtype == np.int8
        assert qt["s"].shape == (4, 1, 96)
        back = np.asarray(wmat(jax.tree.map(jnp.asarray, qt), jnp.float32))
        # symmetric per-channel int8: worst-case error is s/2 per entry
        err = np.abs(back - w)
        bound = np.broadcast_to(np.asarray(qt["s"]) / 2 + 1e-7, w.shape)
        assert (err <= bound).all()
        # and the dequantized matrix is a faithful overall approximation
        rel = np.linalg.norm(back - w) / np.linalg.norm(w)
        assert rel < 0.01, rel


def test_quantized_forward_close_to_full_precision():
    cfg_fp = ModelConfig(dtype="float32", max_model_len=256)
    params = llama.init_params(jax.random.PRNGKey(0), cfg_fp)
    qparams = quantize_params(params, cfg_fp)
    assert is_quantized(qparams["layers"]["wq"])
    assert is_quantized(qparams["lm_head"])
    assert qparams["layers"]["attn_norm"] is params["layers"]["attn_norm"]

    cache = llama.init_cache(cfg_fp, num_pages=16, page_size=8)
    tokens = jnp.arange(2 * 8, dtype=jnp.int32).reshape(2, 8) + 1
    from dynamo_tpu.models.llama import AttnMetadata
    meta = AttnMetadata(
        positions=jnp.tile(jnp.arange(8, dtype=jnp.int32), (2, 1)),
        page_table=jnp.arange(2 * 2, dtype=jnp.int32).reshape(2, 2),
        kv_lens=jnp.full((2,), 8, jnp.int32),
        write_idx=(jnp.arange(2 * 2, dtype=jnp.int32).reshape(2, 2)[
            :, :1] * 8 + jnp.tile(jnp.arange(8, dtype=jnp.int32), (2, 1))))
    ref, _, _ = (llama.forward(params, cfg_fp, tokens, cache, meta)[0],
                 None, None)
    got = llama.forward(qparams, cfg_fp, tokens, cache, meta)[0]
    # int8 per-channel weight error compounds over 2 layers; logits stay
    # close in absolute scale (they are O(1) at init)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.15)


def test_quant_engine_serves_and_halves_weight_bytes():
    eng = NativeEngine(CFG, ECFG, seed=0)
    wq = eng.params["layers"]["wq"]
    assert is_quantized(wq) and wq["q"].dtype == jnp.int8

    def nbytes(t):
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(t))

    fp = NativeEngine(ModelConfig(dtype="float32", max_model_len=256),
                      ECFG, seed=0)
    q_proj = nbytes(eng.params["layers"]["wq"])
    fp_proj = nbytes(fp.params["layers"]["wq"])
    assert q_proj < fp_proj * 0.27  # int8 vs f32 + small scale overhead

    out = eng.generate(list(range(20)),
                       SamplingParams(max_tokens=6, ignore_eos=True), "q")
    assert len(out) == 6
    # same quantized weights -> decode path matches the prefill-consistent
    # greedy continuation deterministically across engines
    eng2 = NativeEngine(CFG, ECFG, seed=0)
    assert eng2.generate(list(range(20)),
                         SamplingParams(max_tokens=6, ignore_eos=True),
                         "q2") == out


def test_quant_engine_tp_and_pp_match_single_device():
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    prompt = list(range(30, 50))
    oracle = NativeEngine(CFG, ECFG, seed=0).generate(prompt, params, "o")

    tp_mesh = make_mesh(tp=2, devices=jax.devices()[:2])
    got_tp = NativeEngine(CFG, ECFG, mesh=tp_mesh, seed=0).generate(
        prompt, params, "tp")
    assert got_tp == oracle, "int8 tp=2 diverged from single-device"

    pp_mesh = make_mesh(pp=2, devices=jax.devices()[:2])
    got_pp = NativeEngine(CFG, ECFG, mesh=pp_mesh, seed=0).generate(
        prompt, params, "pp")
    assert got_pp == oracle, "int8 pp=2 diverged from single-device"


def test_quant_moe_engine_ep_matches_single_device():
    """int8 extends to the stacked expert tensors ([L, E, d, f] with
    per-(layer, expert, out-channel) scales): a quantized MoE engine on
    an ep x tp mesh generates token-for-token with its single-device
    twin, through the O(E/ep) shard_map dispatch (dict-aware in_specs)."""
    moe_cfg = ModelConfig(dtype="float32", quant="int8", max_model_len=256,
                          num_experts=4, num_experts_per_tok=2)
    params = SamplingParams(max_tokens=5, temperature=0.0, ignore_eos=True)
    prompt = list(range(60, 84))
    oracle = NativeEngine(moe_cfg, ECFG, seed=0)
    assert is_quantized(oracle.params["layers"]["w_gate"])
    assert oracle.params["layers"]["w_gate"]["s"].shape[1] == 4  # per-expert
    expect = oracle.generate(prompt, params, "o")

    ep_mesh = make_mesh(ep=4, tp=2, devices=jax.devices()[:8])
    got = NativeEngine(moe_cfg, ECFG, mesh=ep_mesh, seed=0).generate(
        prompt, params, "ep")
    assert got == expect, "int8 ep4xtp2 MoE diverged from single-device"
