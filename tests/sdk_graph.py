"""Fixture service graph for SDK tests (importable by spawned processes).

Shape mirrors the reference's canonical example (reference: examples/llm —
Processor depends on Worker; SURVEY.md §3.2) at toy scale: the Worker
upper-cases tokens, the Processor splits text and fans frames back.
"""
from dynamo_tpu.sdk import async_on_start, depends, endpoint, service
from dynamo_tpu.sdk.config import ServiceConfig


@service(name="EchoWorker", namespace="sdktest", component="worker")
class EchoWorker:
    def __init__(self):
        self.cfg = ServiceConfig.global_instance().for_service("EchoWorker")
        self.prefix = self.cfg.get("prefix", "")
        self.started = False

    @async_on_start
    async def boot(self):
        self.started = True

    @endpoint()
    async def generate(self, request, context):
        assert self.started
        for word in request["text"].split():
            yield {"word": self.prefix + word.upper()}


@service(name="Processor", namespace="sdktest", component="processor")
class Processor:
    worker = depends(EchoWorker)

    @endpoint()
    async def generate(self, request, context):
        n = 0
        stream = await self.worker.generate(request)
        async for frame in stream:
            n += 1
            yield frame
        yield {"count": n}
