"""Round-trip tests for the sampling/output surface the engines must honour:
repetition_penalty, logprobs, n>1 fan-out, echo (VERDICT r2 missing #5;
reference: lib/llm/src/protocols/common.rs SamplingOptions/OutputOptions and
the OpenAI logprobs response fields, openai.rs).
"""
import asyncio
import json

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.frontend.service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.pipeline import LocalPipeline
from dynamo_tpu.llm.worker import NativeEngineWorker

from tests.http_client import request

CFG = ModelConfig(dtype="float32", max_model_len=512)


def make_engine(**kw):
    defaults = dict(page_size=8, num_pages=64, max_slots=4,
                    max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                    max_model_len=512, decode_steps=4)
    defaults.update(kw)
    return NativeEngine(CFG, EngineConfig(**defaults), seed=0)


def byte_card(name="tiny-model"):
    return ModelDeploymentCard(name=name, arch="tiny", tokenizer_kind="byte",
                               context_length=512, eos_token_ids=[2])


# -- engine level --------------------------------------------------------------

def test_repetition_penalty_changes_output():
    """A strong penalty must change the greedy continuation vs rp=1.0 and
    strictly reduce repeats (the tiny random model loops hard without it)."""
    prompt = list(range(50, 66)) * 2  # repetitive prompt encourages loops
    base = make_engine().generate(
        prompt, SamplingParams(max_tokens=24, ignore_eos=True), "base")
    pen = make_engine().generate(
        prompt, SamplingParams(max_tokens=24, ignore_eos=True,
                               repetition_penalty=1.8), "pen")
    assert base != pen
    # penalized run repeats less: count tokens emitted more than once
    def repeats(toks):
        return len(toks) - len(set(toks))
    assert repeats(pen) <= repeats(base)


def test_repetition_penalty_one_is_identity():
    """rp=1.0 must take the unpenalized program and produce identical
    output (the penalized variant is a separate compile; 1.0 must not
    drift)."""
    prompt = list(range(10, 30))
    p1 = make_engine().generate(
        prompt, SamplingParams(max_tokens=8, ignore_eos=True), "a")
    p2 = make_engine().generate(
        prompt, SamplingParams(max_tokens=8, ignore_eos=True,
                               repetition_penalty=1.0), "b")
    assert p1 == p2


def test_logprobs_greedy_sampled_is_top1():
    """Greedy decoding: the sampled token's logprob equals the top-1
    alternative's, and the top-1 id is the sampled token."""
    eng = make_engine()
    eng.add_request(__import__("dynamo_tpu.engine.scheduler",
                               fromlist=["EngineRequest"]).EngineRequest(
        "lp", list(range(20, 40)),
        SamplingParams(max_tokens=6, ignore_eos=True, logprobs=3)))
    events = []
    while eng.has_work():
        events.extend(eng.step())
    toks = [ev for ev in events if ev.token is not None]
    assert toks, events
    for ev in toks:
        assert ev.logprob is not None
        assert ev.top_logprobs is not None and len(ev.top_logprobs) == 3
        top_id, top_lp = ev.top_logprobs[0]
        assert top_id == ev.token
        assert abs(top_lp - ev.logprob) < 1e-5
        assert ev.logprob <= 0.0


# -- HTTP round trips ----------------------------------------------------------

def _serve_native(model="tiny-model"):
    async def setup():
        engine = make_engine()
        worker = await NativeEngineWorker(engine).start()
        pipe = LocalPipeline(byte_card(model), worker)
        svc = await HttpService("127.0.0.1", 0).start()
        svc.models.add(model, pipe, "both")
        return svc, worker
    return setup


def test_completions_logprobs_and_echo_roundtrip():
    async def main():
        svc, worker = await _serve_native()()
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/completions",
            {"model": "tiny-model", "prompt": "hello", "max_tokens": 5,
             "logprobs": 2, "echo": True,
             "ext": {"ignore_eos": True}})
        assert status == 200
        choice = json.loads(body)["choices"][0]
        # echo: response text leads with the prompt
        assert choice["text"].startswith("hello")
        lp = choice["logprobs"]
        assert len(lp["tokens"]) == 5
        assert len(lp["token_logprobs"]) == 5
        assert all(v <= 0.0 for v in lp["token_logprobs"])
        assert all(len(t) == 2 for t in lp["top_logprobs"])
        # text_offset starts after the echoed prompt
        assert lp["text_offset"][0] == len("hello")
        await svc.stop()
        await worker.stop()
    asyncio.run(main())


def test_chat_logprobs_roundtrip():
    async def main():
        svc, worker = await _serve_native()()
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
            {"model": "tiny-model", "max_tokens": 4,
             "messages": [{"role": "user", "content": "hi"}],
             "logprobs": True, "top_logprobs": 2,
             "ext": {"ignore_eos": True}})
        assert status == 200
        choice = json.loads(body)["choices"][0]
        content = choice["logprobs"]["content"]
        assert len(content) == 4
        for entry in content:
            assert entry["logprob"] <= 0.0
            assert len(entry["top_logprobs"]) == 2
            assert isinstance(entry["bytes"], list)
        await svc.stop()
        await worker.stop()
    asyncio.run(main())


def test_logprobs_jailed_by_stop_string():
    """Logprob entries must never cover text a stop string suppressed:
    tokens/text_offset agree exactly with the emitted choice text
    (code-review finding: pre-jail pieces leaked through logprobs)."""
    async def main():
        from dynamo_tpu.llm.worker import EchoTokenEngine
        pipe = LocalPipeline(byte_card("echo"), EchoTokenEngine())
        svc = await HttpService("127.0.0.1", 0).start()
        svc.models.add("echo", pipe, "completion")
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/completions",
            {"model": "echo", "prompt": "hello STOP world",
             "max_tokens": 100, "stop": ["STOP"], "logprobs": 1})
        assert status == 200
        choice = json.loads(body)["choices"][0]
        assert choice["text"] == "hello "
        # EchoTokenEngine sends no logprobs -> the field is simply absent
        lp = choice.get("logprobs")
        assert lp is None or "".join(lp["tokens"]) in choice["text"]
        await svc.stop()
    asyncio.run(main())


def test_logprobs_stop_string_alignment():
    """Stop string + logprobs: the logprobs tokens exactly reconstruct the
    emitted text — entries for jailed/suppressed tokens never appear
    (code-review finding: pre-jail pieces leaked through logprobs)."""
    from dynamo_tpu.protocols.common import EngineOutput, FinishReason

    class AsciiLpEngine:
        """Streams 'worldEND...' one ASCII byte per frame with logprobs."""

        async def generate(self, request, context):
            for ch in "worldEND rest":
                tid = ord(ch) + 3  # ByteTokenizer: id = byte + 3
                yield EngineOutput(
                    token_ids=[tid], log_probs=[-0.5],
                    top_logprobs=[[[float(tid), -0.5]]],
                ).model_dump(exclude_none=True)
            yield EngineOutput(finish_reason=FinishReason.LENGTH
                               ).model_dump(exclude_none=True)

    async def main():
        pipe = LocalPipeline(byte_card("fake"), AsciiLpEngine())
        svc = await HttpService("127.0.0.1", 0).start()
        svc.models.add("fake", pipe, "completion")
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/completions",
            {"model": "fake", "prompt": "say", "max_tokens": 50,
             "logprobs": 1, "stop": ["END"]})
        assert status == 200
        choice = json.loads(body)["choices"][0]
        assert choice["text"] == "world"
        assert choice["finish_reason"] == "stop"
        lp = choice["logprobs"]
        assert "".join(lp["tokens"]) == "world", lp
        assert lp["text_offset"] == list(range(5))
        # without the stop, every token's entry appears
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/completions",
            {"model": "fake", "prompt": "say", "max_tokens": 50,
             "logprobs": 1})
        choice = json.loads(body)["choices"][0]
        assert "".join(choice["logprobs"]["tokens"]) == choice["text"]
        await svc.stop()
    asyncio.run(main())


def test_n_choices_fan_out():
    """n=3 returns 3 indexed choices, each its own engine sample; usage
    counts completion tokens across all choices."""
    async def main():
        svc, worker = await _serve_native()()
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/completions",
            {"model": "tiny-model", "prompt": "abc", "max_tokens": 4,
             "n": 3, "temperature": 0.9, "seed": 7,
             "ext": {"ignore_eos": True}})
        assert status == 200
        out = json.loads(body)
        idxs = sorted(c["index"] for c in out["choices"])
        assert idxs == [0, 1, 2]
        for c in out["choices"]:
            assert c["finish_reason"] == "length"
            assert c["text"]
        assert out["usage"]["completion_tokens"] == 12
        await svc.stop()
        await worker.stop()
    asyncio.run(main())


def test_n_choices_streaming_indexes():
    """Streaming with n=2: chunks carry distinct choice indexes and each
    index gets a finish chunk."""
    async def main():
        from tests.http_client import sse_events
        svc, worker = await _serve_native()()
        seen, finished = set(), set()
        async for _ev, data in sse_events(
                "127.0.0.1", svc.port, "/v1/completions",
                {"model": "tiny-model", "prompt": "xyz", "max_tokens": 3,
                 "n": 2, "stream": True, "ext": {"ignore_eos": True}}):
            if data == "[DONE]":
                break
            for c in json.loads(data)["choices"]:
                seen.add(c["index"])
                if c.get("finish_reason"):
                    finished.add(c["index"])
        assert seen == {0, 1}
        assert finished == {0, 1}
        await svc.stop()
        await worker.stop()
    asyncio.run(main())
