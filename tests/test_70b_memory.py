"""70B scale-out memory evidence (VERDICT r4 #7).

AOT-compiles the llama3-70b pp4 x tp4 plan (decode window + prefill
chunk) on a 16-device virtual mesh in a child process (the in-process
device count is pinned to 8 by conftest) and asserts the per-device
RESIDENT set — sharded bf16 params + paged KV cache + step I/O, net of
donation aliasing — fits a v5e chip's 16 GB HBM with activation headroom.

The resident set is the assertion because it is the cross-platform
invariant XLA reports identically on every backend: if a sharding
regresses (layers replicated, cache unsharded, lm_head unsplit) it jumps
4-16x and this test fails. CPU-reported temp is recorded but not
asserted: the CPU backend materializes layout copies of the scanned
weight stacks (24 GB here) that the TPU compiler never allocates.

Reference bar: the reference serves 70B-class models across nodes via
vLLM pipeline_parallel_size (container/deps/vllm patch vllm_inc.py:38);
this is the equivalent fit-check for our pp4 x tp4 plan.
"""
import json
import os
import subprocess
import sys

V5E_HBM_BYTES = 16_000_000_000
# activations + XLA workspace headroom a real TPU program needs
RESIDENT_BUDGET = int(V5E_HBM_BYTES * 0.75)


def _run_child(extra=()):
    child = os.path.join(os.path.dirname(__file__), "aot_70b_child.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    out = subprocess.run(
        [sys.executable, child, *extra], capture_output=True, text=True,
        timeout=540, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_70b_pp4xtp4_resident_memory_fits_v5e(tmp_path):
    rep = _run_child()
    # sanity: this really is the 70B config, sharded (not replicated)
    assert rep["param_bytes_total"] > 140e9, rep
    per_dev_params_floor = rep["param_bytes_total"] / 16
    assert rep["decode"]["resident"] >= per_dev_params_floor, rep
    # the fit assertion: resident per device within the v5e budget for
    # BOTH the decode window and the batched prefill chunk
    assert rep["decode"]["resident"] <= RESIDENT_BUDGET, rep
    assert rep["prefill"]["resident"] <= RESIDENT_BUDGET, rep


def test_70b_int8_pp2xtp4_fits_half_the_chips(tmp_path):
    """int8 weight-only quantization (ops/quant.py) halves the weight
    bytes, so the same 70B plan fits 8 v5e chips instead of 16."""
    rep = _run_child(("--int8",))
    assert rep["mesh"] == "pp2xtp4", rep
    assert rep["param_bytes_total"] < 75e9, rep  # ~halved vs 141 GB bf16
    assert rep["decode"]["resident"] <= RESIDENT_BUDGET, rep
    assert rep["prefill"]["resident"] <= RESIDENT_BUDGET, rep


def test_mixtral_8x7b_ep4xtp2_fits_v5e8(tmp_path):
    """The MoE flagship's scale-out plan: mixtral-8x7b on 8 v5e chips
    (experts over ep, attention/FFN dims over tp). bf16 fits the raw
    16 GB HBM; int8 (quantized attention + stacked expert tensors) fits
    with the standard activation-headroom budget."""
    child = os.path.join(os.path.dirname(__file__), "aot_mixtral_child.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}

    def run(extra=()):
        out = subprocess.run(
            [sys.executable, child, *extra], capture_output=True,
            text=True, timeout=540, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    bf16 = run()
    assert bf16["param_bytes_total"] > 90e9, bf16
    assert bf16["prefill"]["resident"] >= bf16["param_bytes_total"] / 8
    assert bf16["prefill"]["resident"] <= V5E_HBM_BYTES, bf16

    q = run(("--int8",))
    assert q["param_bytes_total"] < 50e9, q
    assert q["prefill"]["resident"] <= RESIDENT_BUDGET, q
