"""SDK layer tests: decorators/graph collection in-process, then a real
multi-process launch via the supervisor (control-plane server + one process
per service), driven by a runtime client — the reference's `dynamo serve`
flow (SURVEY.md §3.5) end to end.
"""
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_graph_collection_order():
    from tests.sdk_graph import EchoWorker, Processor
    from dynamo_tpu.sdk.service import collect_graph

    specs = collect_graph(Processor)
    assert [s.name for s in specs] == ["EchoWorker", "Processor"]
    proc = Processor.__service_spec__
    assert proc.dependencies == {"worker": EchoWorker}
    assert proc.endpoints == {"generate": "generate"}
    assert EchoWorker.__service_spec__.start_hooks == ["boot"]


def test_chip_allocator():
    from dynamo_tpu.sdk.allocator import ChipAllocator

    alloc = ChipAllocator(4)
    assert alloc.env_for({}) == {"JAX_PLATFORMS": "cpu"}
    env = alloc.env_for({"tpu": 3})
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2"
    with pytest.raises(RuntimeError, match="not enough"):
        alloc.env_for({"tpu": 2})


def test_sdk_graph_multiprocess_roundtrip(tmp_path):
    port = free_port()
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({"EchoWorker": {"prefix": ">"}}))
    sup = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.sdk.serve",
         "tests.sdk_graph:Processor", "-f", str(cfg),
         "--start-control-plane", "--control-port", str(port)],
        stdout=subprocess.PIPE, cwd=REPO, env=ENV, text=True)
    try:
        deadline = 90
        while True:
            line = sup.stdout.readline()
            assert line, "supervisor exited early"
            if line.startswith("READY graph="):
                break

        async def drive():
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            rt = await DistributedRuntime.connect("127.0.0.1", port)
            client = rt.namespace("sdktest").component(
                "processor").endpoint("generate").client()
            await client.start()
            await client.wait_for_instances()
            frames = []
            async for f in await client.generate({"text": "hello tpu"}):
                frames.append(f)
            await client.stop()
            await rt.shutdown()
            return frames

        frames = asyncio.run(asyncio.wait_for(drive(), deadline))
        assert frames == [{"word": ">HELLO"}, {"word": ">TPU"},
                          {"count": 2}]
    finally:
        sup.send_signal(signal.SIGINT)
        try:
            sup.wait(15)
        except subprocess.TimeoutExpired:
            sup.kill()
