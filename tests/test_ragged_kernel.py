"""PR 18: ONE ragged decode kernel + fused sampling tail.

Two gates in one file:

1. The parity matrix — the unified ragged kernel (ops/paged_attention.py)
   against the FROZEN pre-PR-18 kernels (ops/paged_attention_oracle.py),
   across the row vocabulary {plain direct, packed, prefix} x
   {single-device, tp=2 shard_map} x {f32, bf16, int8 scale-folding}.
   The oracle module is the pre-refactor code verbatim, so this matrix IS
   the "token-identical to HEAD" argument at the kernel layer; engine-level
   token identity (greedy + seeded-sampled) rides on top.

2. The fused-sampler contract — `fused` is a static window-key bit:
   common plans (sampled, top_p == 1, no logprobs) dispatch the fused
   argsort-rank tail inside the decode window; uncommon shapes (top_p,
   logprobs, greedy) route to the unfused tail; both produce identical
   tokens (the rank-scatter equivalence argued in docs/PERF.md §3g), and
   a fixed workload compiles the same number of programs either way.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.ops.paged_attention import (
    combine_self_attention, decode_paged_attention,
    decode_paged_attention_prefix, decode_paged_attention_sharded,
)
from dynamo_tpu.ops.paged_attention_oracle import decode_paged_attention_legacy

ECFG = EngineConfig(page_size=8, num_pages=32, max_slots=2,
                    max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                    max_model_len=256)


def _geometry(hd, dtype, quant, seed):
    """Random cache geometry exercising ragged lengths + page reuse."""
    rng = np.random.default_rng(seed)
    s, h, hkv, p, ps, pb = 3, 8, 4, 16, 8, 4
    if hd == 128:
        h, hkv = 4, 2  # keep interpret-mode runtime down at the wide head
    q = rng.standard_normal((s, h, hd)).astype(dtype)
    if quant:
        k = rng.integers(-127, 128, (hkv, p, ps, hd), dtype=np.int8)
        v = rng.integers(-127, 128, (hkv, p, ps, hd), dtype=np.int8)
        ks = rng.uniform(0.01, 0.05, (hkv, p, ps)).astype(np.float32)
        vs = rng.uniform(0.01, 0.05, (hkv, p, ps)).astype(np.float32)
    else:
        k = rng.standard_normal((hkv, p, ps, hd)).astype(dtype)
        v = rng.standard_normal((hkv, p, ps, hd)).astype(dtype)
        ks = vs = None
    pt = ((np.arange(s * pb).reshape(s, pb) * 7) % p).astype(np.int32)
    lens = np.array([5, 17, 32], np.int32)
    return q, k, v, ks, vs, pt, lens


@pytest.mark.parametrize("hd", [32, 64, 128])  # pack = 4 / 2 / 1 (direct)
@pytest.mark.parametrize("dtype,quant", [
    (np.float32, False), (jnp.bfloat16, False), (np.float32, True),
])
def test_unified_matches_legacy_plain(hd, dtype, quant):
    """Plain/packed rows: the unified wrapper == the frozen (s, hkv)-grid
    legacy kernel, bit-for-shape across pack factors, bf16 DMA, and the
    int8 scale fold."""
    q, k, v, ks, vs, pt, lens = _geometry(hd, dtype, quant, seed=hd)
    kw = dict(interpret=True)
    if quant:
        kw.update(k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    out = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pt), jnp.asarray(lens), **kw)
    ref = decode_paged_attention_legacy(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pt), jnp.asarray(lens), **kw)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol)


@pytest.mark.parametrize("hd", [64, 128])
def test_unified_prefix_matches_legacy_inclusive(hd):
    """Prefix rows: prefix-mode kernel + combine_self_attention over a
    cache WITHOUT the current token == the legacy inclusive kernel over
    the cache WITH the token scattered in — the deferred-write decode hot
    path against the frozen pre-PR-18 implementation, including an empty
    prefix row."""
    rng = np.random.default_rng(hd)
    s, h, hkv, L, p, ps, pb = 3, 8, 2, 2, 16, 64, 3
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    kc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
    vc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
    k_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
    v_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
    # DISJOINT per-row pages: the inclusive reference scatters each row's
    # current token into its boundary page, so no page may be shared
    pt = np.arange(s * pb).reshape(s, pb).astype(np.int32)
    prefix = np.array([70, 0, 130], np.int32)
    layer = 1

    acc, m, l = decode_paged_attention_prefix(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray([layer], jnp.int32), jnp.asarray(pt),
        jnp.asarray(prefix), interpret=True)
    out = combine_self_attention(
        jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new), acc, m, l)

    # scatter the current token into row prefix[i] of its boundary page
    # and ask the frozen inclusive kernel the same question
    k_inc, v_inc = kc[layer].copy(), vc[layer].copy()
    for i in range(s):
        pg, r = pt[i, prefix[i] // ps], prefix[i] % ps
        k_inc[:, pg, r] = k_new[i]
        v_inc[:, pg, r] = v_new[i]
    ref = decode_paged_attention_legacy(
        jnp.asarray(q), jnp.asarray(k_inc), jnp.asarray(v_inc),
        jnp.asarray(pt), jnp.asarray(prefix + 1), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_unified_prefix_int8_scale_fold_matches_dequant():
    """Prefix rows x int8: in-kernel scale folding == running the same
    unified kernel on the explicitly dequantized f32 cache (the exactness
    argument: a row's scale is constant over the hd contraction, so it
    commutes with both kernel dots)."""
    rng = np.random.default_rng(9)
    s, h, hkv, L, p, ps, pb, hd = 3, 8, 2, 2, 8, 64, 3, 64
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    kc = rng.integers(-127, 128, (L, hkv, p, ps, hd), dtype=np.int8)
    vc = rng.integers(-127, 128, (L, hkv, p, ps, hd), dtype=np.int8)
    ks = rng.uniform(0.01, 0.05, (L, hkv, p, ps)).astype(np.float32)
    vs = rng.uniform(0.01, 0.05, (L, hkv, p, ps)).astype(np.float32)
    pt = ((np.arange(s * pb).reshape(s, pb) * 3) % p).astype(np.int32)
    prefix = np.array([70, 0, 130], np.int32)

    quant = decode_paged_attention_prefix(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray([1], jnp.int32), jnp.asarray(pt), jnp.asarray(prefix),
        interpret=True, k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    deq = decode_paged_attention_prefix(
        jnp.asarray(q),
        jnp.asarray(kc.astype(np.float32) * ks[..., None]),
        jnp.asarray(vc.astype(np.float32) * vs[..., None]),
        jnp.asarray([1], jnp.int32), jnp.asarray(pt), jnp.asarray(prefix),
        interpret=True)
    for a, b in zip(quant, deq):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("quant", [False, True])
def test_sharded_tp2_matches_legacy(quant):
    """tp=2 shard_map'd unified kernel == single-device legacy kernel
    (heads sharded; int8 shards the scale stacks the same way)."""
    from dynamo_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    q, k, v, ks, vs, pt, lens = _geometry(32, np.float32, quant, seed=5)
    kw = dict(interpret=True)
    if quant:
        kw.update(k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    mesh = make_mesh(tp=2)
    out = decode_paged_attention_sharded(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pt), jnp.asarray(lens), mesh, **kw)
    ref = decode_paged_attention_legacy(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pt), jnp.asarray(lens), **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# -- engine-level token identity ----------------------------------------------

SAMPLED = SamplingParams(max_tokens=6, temperature=0.8, top_k=40,
                         seed=1234, ignore_eos=True)
PROMPT = list(range(50, 70))


def _gen(mcfg, ecfg=ECFG, mesh=None, params=SAMPLED, rid="r"):
    eng = NativeEngine(mcfg, ecfg, mesh=mesh, seed=0)
    try:
        return eng.generate(PROMPT, params, rid), eng
    finally:
        eng.close()


@pytest.mark.parametrize("mesh_kw", [None, {"tp": 2}])
def test_engine_sampled_kernel_matches_gather(mesh_kw):
    """Seeded-sampled engine runs (the fused-tail path: top_p == 1) are
    token-identical between the unified ragged kernel and the XLA gather
    path, single-device and tp=2 shard_map."""
    from dynamo_tpu.parallel.mesh import make_mesh
    if mesh_kw and len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh(**mesh_kw) if mesh_kw else None
    base = ModelConfig(dtype="float32", max_model_len=256)
    off, _ = _gen(dataclasses.replace(base, decode_kernel="off"), mesh=mesh)
    kern, _ = _gen(dataclasses.replace(base, decode_kernel="interpret"),
                   mesh=mesh)
    assert off == kern


@pytest.mark.parametrize("params", [
    SamplingParams(max_tokens=5, temperature=0.0, ignore_eos=True),
    SAMPLED,
])
def test_engine_int8_kernel_matches_gather(params):
    """int8 kv_quant x {greedy, seeded-sampled}: the in-kernel scale fold
    decodes the same tokens as the gather path's row dequant."""
    base = ModelConfig(dtype="float32", max_model_len=256)
    ecfg = dataclasses.replace(ECFG, kv_quant="int8")
    off, _ = _gen(dataclasses.replace(base, decode_kernel="off"), ecfg,
                  params=params)
    kern, _ = _gen(dataclasses.replace(base, decode_kernel="interpret"),
                   ecfg, params=params)
    assert off == kern


# -- fused sampling tail: routing, identity, recompiles -----------------------


def test_fused_bit_routing():
    """The fused tail runs exactly for common plans: sampled with
    top_p == 1 and no logprobs. top_p < 1, logprobs, and greedy all fall
    back to the unfused tail (token-identically — the tail bit never
    changes WHAT is sampled, only how the ranks are materialized)."""
    base = ModelConfig(dtype="float32", max_model_len=256)
    _, eng = _gen(base)
    assert eng.decode_kernel_tag.endswith("+fused")
    assert eng.decode_dispatches == eng.decode_windows > 0
    _, eng = _gen(base, params=dataclasses.replace(SAMPLED, top_p=0.9))
    assert "+fused" not in eng.decode_kernel_tag
    _, eng = _gen(base, params=dataclasses.replace(SAMPLED, logprobs=0))
    assert "+fused" not in eng.decode_kernel_tag
    _, eng = _gen(base, params=SamplingParams(max_tokens=5, temperature=0.0,
                                              ignore_eos=True))
    assert "+fused" not in eng.decode_kernel_tag


def test_fused_equals_unfused_tokens(monkeypatch):
    """Forcing the unfused tail on a fused-eligible workload reproduces
    the exact token stream (docs/PERF.md §3g rank-scatter equivalence)."""
    from dynamo_tpu.engine import sampler as sampler_mod
    base = ModelConfig(dtype="float32", max_model_len=256)
    fused, eng = _gen(base)
    assert eng.decode_kernel_tag.endswith("+fused")
    monkeypatch.setattr(sampler_mod.SamplingArrayCache, "fused_eligible",
                        property(lambda self: False))
    unfused, eng = _gen(base)
    assert "+fused" not in eng.decode_kernel_tag
    assert fused == unfused


def test_fused_mixed_batch_tokens_identical(monkeypatch):
    """A mixed batch (one greedy row via temperature 0, one sampled row)
    stays fused-eligible — sample_fused resolves temp <= 0 rows to argmax
    in-program — and matches the unfused tail row for row."""
    from dynamo_tpu.engine import sampler as sampler_mod
    from dynamo_tpu.engine.scheduler import EngineRequest
    base = ModelConfig(dtype="float32", max_model_len=256)
    reqs = [
        ("greedy", SamplingParams(max_tokens=6, temperature=0.0,
                                  ignore_eos=True)),
        ("sampled", dataclasses.replace(SAMPLED, seed=77)),
    ]

    def run():
        eng = NativeEngine(base, ECFG, seed=0)
        toks = {rid: [] for rid, _ in reqs}
        for rid, p in reqs:
            eng.add_request(EngineRequest(rid, PROMPT, p))
        try:
            while eng.has_work():
                for ev in eng.step():
                    if ev.token is not None:
                        toks[ev.request_id].append(ev.token)
            return toks
        finally:
            eng.close()

    fused = run()
    monkeypatch.setattr(sampler_mod.SamplingArrayCache, "fused_eligible",
                        property(lambda self: False))
    assert run() == fused


def test_fused_flag_is_static_no_recompiles():
    """Recompile pin (_note_program): the fused bit is part of the staged
    window's program key and constant for a fixed workload — a second
    identical request mints ZERO new programs."""
    base = ModelConfig(dtype="float32", max_model_len=256)
    eng = NativeEngine(base, ECFG, seed=0)
    try:
        eng.generate(PROMPT, SAMPLED, "a")
        programs = set(eng._seen_programs)
        # distinct same-length prompt: prefix-cache reuse would otherwise
        # legitimately shrink request b's prefill chunk (a different
        # program, but not a fused-bit recompile)
        eng.generate([t + 100 for t in PROMPT],
                     dataclasses.replace(SAMPLED, seed=99), "b")
        assert eng._seen_programs == programs
    finally:
        eng.close()
