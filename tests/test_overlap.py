"""Early decode over the committed frontier (FlowKV-style overlap).

The disagg decode worker no longer waits for KV-stream completion: the
prefill side publishes a `transfer_pending` completion the moment it
samples the first token, the decode worker emits that token immediately
(TTFT stops paying the transfer), and decode activation gates on the
scheduler's per-request committed-frontier watermark
(engine/scheduler.py overlap gates) — checked before planning, opened
by the KvTransferServer's chunk commits.

Pinned here:
- token identity: overlap on == overlap off == aggregated oracle, for
  greedy AND seeded-sampled streams (reading only committed pages is
  exact — docs/PERF.md);
- span ordering: the first decode window runs before the final chunk's
  ack lands sender-side (`decode.emit` precedes the `kv.transfer`
  span's end);
- failure semantics unchanged: sender death mid-overlap still salvages
  the committed prefix with `majority_committed_full_reprefills == 0`,
  and the already-emitted first token is charged, never re-emitted;
- the wait-for-completion mode still works (early notifies ignored).
"""
import asyncio

import pytest

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer, PrefillQueue,
    PrefillWorker, RemoteTransferBackend,
)
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.llm.worker import NativeEngineWorker
from dynamo_tpu.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.faults import FaultSchedule, FaultSpec
from dynamo_tpu.runtime.integrity import XFER_STATS
from dynamo_tpu.runtime.tracing import TRACE_KEY, TRACER, TraceContext
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()
    TRACER.configure(enabled=False, sample_rate=1.0, seed=0)
    TRACER.reset()


def make_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


def pre_request(rid, prompt, max_tokens=6, temperature=0.0, seed=0):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        sampling=SamplingOptions(temperature=temperature, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True))


async def _drive(gen):
    toks, reason = [], None
    async for frame in gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reason = frame["finish_reason"]
    return toks, reason


async def _build_stack(plane, early_decode=True, chunk_pages=1,
                       window_chunks=1, prefill_timeout_s=30.0):
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=8, model="tiny")
    decode = DisaggDecodeWorker(
        make_engine(), plane.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=prefill_timeout_s,
        early_decode=early_decode)
    server = await KvTransferServer(decode, "dec-0").start()
    await server.register(plane.kv)
    transfer = RemoteTransferBackend(plane.kv, chunk_pages=chunk_pages,
                                     window_chunks=window_chunks)
    prefill = PrefillWorker(
        NativeEngineWorker(make_engine()), queue, transfer, plane.messaging)
    return decode, prefill, server, transfer


def _run_disagg(pre, early_decode=True, arm=None, trace=None,
                link_retries=3):
    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_stack(
            plane, early_decode=early_decode)
        transfer.link_retries = link_retries
        if arm is not None:
            faults.REGISTRY.arm("transfer.link", arm)
        await decode.start()
        await prefill.start()
        ctx = (Context(pre.request_id,
                       baggage={TRACE_KEY: trace.to_wire()})
               if trace is not None else Context(pre.request_id))
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre.model_dump(exclude_none=True), ctx)),
                120)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, reason, decode

    return asyncio.run(main())


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_overlap_token_identity_greedy_and_sampled(temperature):
    """Overlap on == overlap off == aggregated oracle: activation waits
    for exactly the pages the first window reads, so the engine state at
    activation is bit-identical to wait-for-completion — only the wall
    clock differs."""
    prompt = list(range(100, 140))   # 5 pages -> 5 chunks
    params = SamplingParams(max_tokens=6, temperature=temperature,
                            seed=7, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    toks_on, reason_on, dec_on = _run_disagg(
        pre_request("ov1", prompt, temperature=temperature, seed=7))
    toks_off, reason_off, dec_off = _run_disagg(
        pre_request("ov2", prompt, temperature=temperature, seed=7),
        early_decode=False)
    assert reason_on == reason_off == "length"
    assert toks_on == toks_off == expect
    # the overlap run really overlapped; the disabled run never did
    assert dec_on.early_first_emits == 1
    assert dec_on.engine.scheduler.overlap_activations == 1
    assert dec_on.overlap_fallbacks == 0
    assert dec_off.early_first_emits == 0
    assert dec_off.engine.scheduler.overlap_activations == 0


def test_first_decode_window_precedes_final_chunk_ack():
    """The acceptance ordering: with a per-chunk stalled link the first
    decode emit lands BEFORE the sender's kv.transfer span ends (= the
    final chunk's ack) — decode genuinely runs under the in-flight
    tail."""
    TRACER.configure(enabled=True, sample_rate=1.0)
    TRACER.reset()
    prompt = list(range(100, 140))   # 5 chunks at chunk_pages=1
    # deterministic 60ms stall per chunk: the transfer tail is wide
    # enough that span ordering cannot be won by scheduling luck
    arm = FaultSchedule(0, [FaultSpec("delay", p=1.0, delay_s=0.06,
                                      delay_min_s=0.06)])
    trace = TraceContext("ov-trace")
    toks, reason, dec = _run_disagg(
        pre_request("ov3", prompt, max_tokens=4), arm=arm, trace=trace)
    assert reason == "length" and len(toks) == 4
    assert dec.early_first_emits == 1
    spans = TRACER.drain()
    emits = [s for s in spans if s["name"] == "decode.emit"
             and (s.get("attrs") or {}).get("first")]
    xfers = [s for s in spans if s["name"] == "kv.transfer"]
    assert emits and xfers
    first_emit = min(s["ts"] for s in emits)
    xfer_end = max(s["ts"] + s["dur"] for s in xfers)
    assert first_emit < xfer_end, \
        "first token emit did not precede the transfer's last ack"
    # the first decode WINDOW also starts before the final chunk acks:
    # at least one non-first decode.emit (the engine's own output) lands
    # before the transfer span ends only when the gate+decode genuinely
    # ran under the tail — with a 60ms/chunk stall and 5 chunks the
    # final chunks are still streaming when decode begins. The chunk
    # spans prove the interleave: the LAST chunk span starts after the
    # first emit.
    chunks = [s for s in spans if s["name"] == "kv.transfer.chunk"]
    assert chunks
    last_chunk_start = max(s["ts"] for s in chunks)
    assert first_emit < last_chunk_start, \
        "first emit should precede the final chunk's send"


def test_sender_death_mid_overlap_salvages_committed_prefix():
    """Link permanently dead after 3 of 5 chunks committed, resume
    budget exhausted, first token ALREADY emitted: the decode worker
    salvages the committed pages, seeds the emitted token, re-prefills
    only the tail — token-identical, no re-emit, tripwire clean."""
    prompt = list(range(50, 90))   # 5 pages; chunks 0-2 commit
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")
    s0 = XFER_STATS.salvaged_pages
    arm = FaultSchedule(0, [FaultSpec("fail_n", n=1000, skip=3)])
    toks, reason, dec = _run_disagg(
        pre_request("ovs", prompt), arm=arm, link_retries=1)
    assert reason == "length" and toks == expect
    assert dec.early_first_emits == 1
    assert dec.overlap_fallbacks == 1
    assert dec.salvaged_prefills == 1 and dec.full_reprefills == 0
    assert dec.majority_committed_full_reprefills == 0
    assert XFER_STATS.salvaged_pages - s0 == 3
    # the emitted first token was charged, not recomputed differently:
    # exactly max_tokens tokens reached the client (no duplicate first)
    assert len(toks) == 6


def test_overlap_full_fallback_when_nothing_committed():
    """Link dead from chunk 0 with the first token already emitted:
    nothing committed -> full local re-prefill through the committed-
    prefix resume machinery; the stream still matches the oracle and
    the first token is never re-emitted."""
    prompt = list(range(60, 100))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")
    arm = FaultSchedule(0, [FaultSpec("fail_n", n=1000)])
    toks, reason, dec = _run_disagg(
        pre_request("ovf", prompt), arm=arm, link_retries=0)
    assert reason == "length" and toks == expect
    assert dec.early_first_emits == 1
    assert dec.overlap_fallbacks == 1
    assert dec.full_reprefills == 1 and dec.salvaged_prefills == 0
    assert dec.majority_committed_full_reprefills == 0
    assert len(toks) == 6


# -- scheduler-level gate unit coverage ---------------------------------------


def test_overlap_gate_promotes_exactly_at_watermark():
    eng = make_engine()
    prompt = list(range(100, 140))   # 5 pages
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    alloc = eng.allocate_remote(EngineRequest("g1", prompt, params))
    assert alloc is not None
    frontier = {"v": 0}
    eng.preactivate_remote("g1", 321, len(alloc.page_ids),
                           lambda: frontier["v"])
    # below the watermark: no activation, seq stays remote
    assert not eng.has_work()
    assert "g1" in eng.scheduler.remote
    frontier["v"] = len(alloc.page_ids) - 1
    assert not eng.has_work()
    # at the watermark: promoted into the normal waiting flow
    frontier["v"] = len(alloc.page_ids)
    assert eng.has_work()
    assert "g1" not in eng.scheduler.remote
    assert eng.scheduler.overlap_activations == 1
    seq = eng.scheduler.waiting[0]
    assert seq.output == [321]


def test_overlap_gate_cancel_and_release_semantics():
    eng = make_engine()
    prompt = list(range(100, 132))
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    alloc = eng.allocate_remote(EngineRequest("g2", prompt, params))
    eng.preactivate_remote("g2", 5, len(alloc.page_ids), lambda: 0)
    # pending gate: cancel reports True and decode never activates
    assert eng.cancel_overlap("g2") is True
    assert eng.cancel_overlap("g2") is False   # already disarmed
    assert "g2" in eng.scheduler.remote        # allocation untouched
    # release drops a still-armed gate with the allocation
    alloc2 = eng.allocate_remote(EngineRequest("g3", prompt, params))
    eng.preactivate_remote("g3", 5, len(alloc2.page_ids), lambda: 0)
    eng.release_remote("g3")
    assert not eng.scheduler.overlap_gates
    assert not eng.has_work()
