"""Protocol layer tests: SSE codec, incremental detokenize, stop jail,
preprocessor (template+tokenize+defaults+annotations)."""
from dynamo_tpu.llm.backend import BackendPostprocessor, StopJail
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import ByteTokenizer, DecodeStream
from dynamo_tpu.protocols.common import EngineOutput, FinishReason
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest, ChatMessage, CompletionRequest, Ext,
)
from dynamo_tpu.protocols.sse import (
    SseEvent, decode_stream, encode_event, encode_json_data,
)


def test_sse_roundtrip_with_edge_cases():
    text = (
        encode_event(SseEvent(comments=["keepalive"]))
        + encode_event(SseEvent(data='{"a":1}', event="annotation", id="7"))
        + encode_event(SseEvent(data="line1\nline2"))
        + "data: [DONE]\n\n"
    )
    events = list(decode_stream(text))
    assert events[0].comments == ["keepalive"] and events[0].data is None
    assert events[1].data == '{"a":1}' and events[1].event == "annotation"
    assert events[1].id == "7"
    assert events[2].data == "line1\nline2"
    assert events[3].is_done


def test_encode_json_data():
    assert encode_json_data({"x": 1}) == 'data: {"x":1}\n\n'


def test_decode_stream_utf8_boundary():
    tok = ByteTokenizer()
    ids = tok.encode("héllo ✓")
    ds = DecodeStream(tok)
    out = "".join(ds.step(i) for i in ids)
    assert out == "héllo ✓"
    # multi-byte glyphs must never emit partial replacement chars
    ds2 = DecodeStream(tok)
    pieces = [ds2.step(i) for i in tok.encode("✓")]
    assert "".join(pieces) == "✓"
    assert all("�" not in p for p in pieces)


def test_stop_jail_partial_and_full():
    jail = StopJail(["STOP"])
    out, stopped = jail.push("hello ST")
    assert out == "hello " and not stopped  # "ST" held as possible prefix
    out, stopped = jail.push("ILL")  # resolves to not-a-stop
    assert out == "STILL" and not stopped
    out, stopped = jail.push(" and STOP now")
    assert out == " and " and stopped


def test_backend_postprocessor_end_to_end():
    tok = ByteTokenizer()
    bp = BackendPostprocessor(tok, stop_strings=["</s>"])
    r1 = bp.process(EngineOutput(token_ids=tok.encode("hi the")))
    r2 = bp.process(EngineOutput(token_ids=tok.encode("re</s>ignored")))
    assert r1.text + r2.text == "hi there"
    assert r2.finish_reason == FinishReason.STOP


def test_preprocessor_chat_template_and_defaults():
    card = ModelDeploymentCard(name="m", context_length=128)
    pre = OpenAIPreprocessor(card)
    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="hi")],
        max_tokens=10, temperature=0.5, stop="END",
        ext=Ext(annotations=["token_ids", "formatted_prompt"], top_k=5),
    )
    out, anns = pre.preprocess_chat(req, "rid")
    assert out.request_id == "rid"
    assert out.token_ids == pre.tokenizer.encode("<|user|>hi</s><|assistant|>")
    assert out.stop.max_tokens == 10
    assert out.stop.stop == ["END"]
    assert out.sampling.temperature == 0.5
    assert out.sampling.top_k == 5
    assert out.eos_token_ids == [2]
    assert {a.event for a in anns} == {"token_ids", "formatted_prompt"}
    assert out.mdc_sum == card.mdcsum


def test_preprocessor_completion_and_token_prompt():
    card = ModelDeploymentCard(name="m", context_length=64)
    pre = OpenAIPreprocessor(card)
    out, _ = pre.preprocess_completion(
        CompletionRequest(model="m", prompt="abc", max_tokens=99))
    # max_tokens clamped to remaining context
    assert out.stop.max_tokens == 61
    assert out.token_ids == pre.tokenizer.encode("abc")
    out2, _ = pre.preprocess_completion(
        CompletionRequest(model="m", prompt=[5, 6, 7]))
    assert out2.token_ids == [5, 6, 7]


def test_greed_sampling_ext():
    card = ModelDeploymentCard(name="m")
    pre = OpenAIPreprocessor(card)
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="x")],
        temperature=0.9, ext=Ext(greed_sampling=True))
    out, _ = pre.preprocess_chat(req)
    assert out.sampling.temperature == 0.0


def test_model_card_roundtrip_and_checksum():
    card = ModelDeploymentCard(name="m", arch="tiny", context_length=512)
    d = card.to_dict()
    card2 = ModelDeploymentCard.from_dict(d)
    assert card2 == card
    assert card.mdcsum == card2.mdcsum
    card3 = ModelDeploymentCard(name="m2", arch="tiny", context_length=512)
    assert card3.mdcsum != card.mdcsum
