"""Test configuration: force CPU with an 8-device virtual mesh.

Mirrors the reference's hardware-independent test strategy (SURVEY.md §4.5):
the reference tests its runtime with closure engines and a mock network; we
test our JAX engine and sharding on a virtual 8-device CPU mesh so no TPU is
required.

NOTE: this image registers the TPU backend via sitecustomize and pins
jax_platforms programmatically, so an env-var override is not enough — we must
set the config knob after importing jax (before any backend init).
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the full suite on one CPU core can starve lease heartbeats past TTL/3,
# falsely expiring workers mid-test (observed flake: kv-events test);
# tests that exercise expiry override dist.LEASE_TTL_S directly
os.environ.setdefault("DYN_LEASE_TTL_S", "60")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent XLA compilation cache: the suite builds dozens of engines
# whose tiny-model programs are HLO-identical (oracle/twin pairs, module
# fixtures across files); the disk cache dedupes them ACROSS engine
# instances and pytest runs — measured 25s -> 8s on test_mixed_steps
# alone, and it is the difference between the full suite fitting its
# 870s tier-1 budget and timing out. Keyed by HLO+config hash, so
# config/backend changes can never serve a stale program.
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(_repo, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import gc  # noqa: E402

import pytest  # noqa: E402

_gc_epoch = [0]


@pytest.fixture(autouse=True)
def _finalize_asyncio_cycles_between_tests():
    """Collect cyclic garbage after every test, BEFORE the next test
    opens sockets. A test that abandons asyncio objects mid-flight (e.g.
    after SIGKILLing a peer process, test_queue_push_survives_sigkill)
    leaves transport<->protocol<->task cycles for the cycle collector;
    if that collection happens during a LATER test's event loop, the
    stale transports' __del__ close raw fd NUMBERS that the new loop has
    since reused for its own sockets — observed as the next test's
    streams silently hanging to their 30s/60s timeouts. The collect runs
    at SETUP of the following test (pytest itself keeps the previous
    item's frames referenced until the next one begins, so teardown-time
    collection finds the cycles still live), closing those fds while the
    numbers are still unused.

    A FULL collect scans every tracked object, and the suite's heap only
    grows (jit program caches, module state): measured ~0.07s/test early
    in the run but ~1.4s/test by test 600 — 583s of an 1123s full-suite
    wall, tipping tier-1 past its 870s budget. gc.freeze() moves the
    stable baseline out of the per-test scan, so each collect only walks
    objects allocated since the last freeze (the previous few tests —
    exactly where abandoned transport cycles live, since freezes also
    happen at setup, before any of the current window's tests ran).
    Every 50 tests, unfreeze + full collect + refreeze at this same safe
    point reclaims anything that was live at an earlier freeze and has
    died since, so frozen-then-dead cycles (and their fds) are bounded
    to a 50-test window instead of leaking for the whole run."""
    if _gc_epoch[0] % 50 == 0:
        gc.unfreeze()
        gc.collect()
        gc.freeze()
    else:
        gc.collect()
    _gc_epoch[0] += 1
    yield
