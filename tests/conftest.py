"""Test configuration: force CPU with an 8-device virtual mesh.

Mirrors the reference's hardware-independent test strategy (SURVEY.md §4.5):
the reference tests its runtime with closure engines and a mock network; we
test our JAX engine and sharding on a virtual 8-device CPU mesh so no TPU is
required.

NOTE: this image registers the TPU backend via sitecustomize and pins
jax_platforms programmatically, so an env-var override is not enough — we must
set the config knob after importing jax (before any backend init).
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the full suite on one CPU core can starve lease heartbeats past TTL/3,
# falsely expiring workers mid-test (observed flake: kv-events test);
# tests that exercise expiry override dist.LEASE_TTL_S directly
os.environ.setdefault("DYN_LEASE_TTL_S", "60")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
