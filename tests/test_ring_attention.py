"""Ring attention (sequence parallel) vs dense oracle on the 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.attention import dense_causal_attention
from dynamo_tpu.ops.ring_attention import ring_attention
from dynamo_tpu.parallel.mesh import make_mesh


def _rand(rng, shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def test_ring_attention_matches_dense():
    mesh = make_mesh(sp=8)
    rng = np.random.default_rng(0)
    b, t, h, hkv, hd = 2, 64, 4, 2, 16
    q = _rand(rng, (b, t, h, hd))
    k = _rand(rng, (b, t, hkv, hd))
    v = _rand(rng, (b, t, hkv, hd))
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    out = ring_attention(q, k, v, positions, positions, mesh)
    expected = dense_causal_attention(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_padding_masked():
    """-1 positions (padding) must not contribute and must not NaN."""
    mesh = make_mesh(sp=4)
    rng = np.random.default_rng(1)
    b, t, h, hkv, hd = 1, 32, 2, 1, 8
    valid = 19
    q = _rand(rng, (b, t, h, hd))
    k = _rand(rng, (b, t, hkv, hd))
    v = _rand(rng, (b, t, hkv, hd))
    positions = np.full((b, t), -1, np.int32)
    positions[0, :valid] = np.arange(valid)
    positions = jnp.asarray(positions)

    out = np.asarray(ring_attention(q, k, v, positions, positions, mesh))
    assert np.isfinite(out).all()
    # valid prefix must match the dense oracle on the valid slice
    expected = dense_causal_attention(
        q[:, :valid], k[:, :valid], v[:, :valid],
        jnp.arange(valid, dtype=jnp.int32)[None, :])
    np.testing.assert_allclose(out[:, :valid], np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_jit_under_mesh():
    """jit(ring_attention) compiles once and matches eager."""
    mesh = make_mesh(sp=8)
    rng = np.random.default_rng(2)
    b, t, h, hkv, hd = 1, 64, 4, 4, 16
    q = _rand(rng, (b, t, h, hd))
    k = _rand(rng, (b, t, hkv, hd))
    v = _rand(rng, (b, t, hkv, hd))
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    jitted = jax.jit(lambda *a: ring_attention(*a, mesh))
    out = jitted(q, k, v, positions, positions)
    expected = dense_causal_attention(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)
