"""HF checkpoint loading: logit parity against transformers (torch CPU).

The strongest correctness check available without network access: build a
tiny random HF model with transformers, save_pretrained it, load the
checkpoint with our loader, and require logits to match the torch forward
pass. Covers tensor-name mapping, transposes, RoPE convention, RMSNorm, GQA,
attention bias (Qwen2), and MoE expert weights (Mixtral).
"""
import json

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import AttnMetadata
from dynamo_tpu.models.loader import config_from_hf, load_model_dir

torch = pytest.importorskip("torch")


def our_logits(cfg, params, tokens):
    t = len(tokens)
    ps = 8
    n_pages = (t + ps - 1) // ps + 1
    cache = llama.init_cache(cfg, n_pages, ps)
    meta = AttnMetadata(
        positions=jnp.arange(t, dtype=jnp.int32)[None],
        page_table=jnp.arange(n_pages, dtype=jnp.int32)[None],
        kv_lens=jnp.asarray([t], jnp.int32),
        write_idx=jnp.arange(t, dtype=jnp.int32)[None],
    )
    logits, _ = llama.forward(params, cfg,
                              jnp.asarray(np.asarray(tokens))[None],
                              cache, meta)
    return np.asarray(logits[0])


def hf_logits(model, tokens):
    with torch.no_grad():
        out = model(torch.tensor([list(tokens)]))
    return out.logits[0].float().numpy()


def roundtrip(tmp_path, hf_config, model_cls):
    torch.manual_seed(0)
    model = model_cls(hf_config)
    model.eval()
    path = tmp_path / "model"
    model.save_pretrained(path, safe_serialization=True)
    cfg, params = load_model_dir(str(path), dtype="float32")
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, hf_config.vocab_size, 12).astype(np.int32)
    ours = our_logits(cfg, params, tokens)
    theirs = hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    return cfg


def test_llama_checkpoint_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM
    hf = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=128,
                     rope_theta=10000.0, tie_word_embeddings=False)
    cfg = roundtrip(tmp_path, hf, LlamaForCausalLM)
    assert not cfg.attn_bias and not cfg.is_moe


def test_llama_tied_embeddings_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM
    hf = LlamaConfig(vocab_size=96, hidden_size=48, intermediate_size=96,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=128,
                     tie_word_embeddings=True)
    cfg = roundtrip(tmp_path, hf, LlamaForCausalLM)
    assert cfg.tie_word_embeddings


def test_qwen2_checkpoint_parity(tmp_path):
    from transformers import Qwen2Config, Qwen2ForCausalLM
    hf = Qwen2Config(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=128,
                     tie_word_embeddings=False)
    cfg = roundtrip(tmp_path, hf, Qwen2ForCausalLM)
    assert cfg.attn_bias


def test_mixtral_checkpoint_parity(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM
    hf = MixtralConfig(vocab_size=128, hidden_size=64, intermediate_size=96,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, num_local_experts=4,
                       num_experts_per_tok=2, max_position_embeddings=128,
                       tie_word_embeddings=False)
    # dense-compute MoE is the exact oracle; dispatch drops are a separate
    # concern (tested in test_model.py)
    import dataclasses
    torch.manual_seed(0)
    model = MixtralForCausalLM(hf)
    model.eval()
    path = tmp_path / "model"
    model.save_pretrained(path, safe_serialization=True)
    cfg, params = load_model_dir(str(path), dtype="float32")
    cfg = dataclasses.replace(cfg, moe_impl="dense")
    assert cfg.num_experts == 4
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, hf.vocab_size, 12).astype(np.int32)
    ours = our_logits(cfg, params, tokens)
    theirs = hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_engine_serves_hf_checkpoint_greedy_parity(tmp_path):
    """Full stack: card from HF dir -> loaded weights -> NativeEngine greedy
    decode must reproduce transformers' greedy generation."""
    from transformers import LlamaConfig, LlamaForCausalLM

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.models.loader import load_params_from_hf

    hf = LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=128,
                     torch_dtype="float32")
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf)
    model.eval()
    path = tmp_path / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    card = ModelDeploymentCard.from_hf_dir(str(path))
    cfg = card.model_config()
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype="float32")
    params = load_params_from_hf(str(path), cfg)
    engine = NativeEngine(cfg, EngineConfig(
        page_size=8, num_pages=32, max_slots=2, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=128), params=params)

    prompt = list(np.random.default_rng(1).integers(1, 512, 10))
    n_new = 6
    got = engine.generate([int(t) for t in prompt],
                          SamplingParams(max_tokens=n_new, temperature=0.0,
                                         ignore_eos=True), "hf")
    with torch.no_grad():
        out = model.generate(torch.tensor([prompt]), max_new_tokens=n_new,
                             do_sample=False, eos_token_id=None)
    expect = out[0, len(prompt):].tolist()
    assert got == expect


def test_gemma_checkpoint_parity(tmp_path):
    """Gemma family: sqrt(d) embedding scale, (1+w) RMSNorm in f32,
    tanh-GELU GLU, head_dim independent of hidden/heads, tied embeddings
    (the HF GemmaConfig default)."""
    from transformers import GemmaConfig, GemmaForCausalLM
    hf = GemmaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, head_dim=24,
                     max_position_embeddings=128, rope_theta=10000.0)
    cfg = roundtrip(tmp_path, hf, GemmaForCausalLM)
    assert cfg.tie_word_embeddings and cfg.norm_plus_one
    assert cfg.mlp_act == "gelu_tanh" and cfg.head_dim == 24
    assert abs(cfg.embed_scale - 8.0) < 1e-9


def test_gemma2_checkpoint_parity(tmp_path):
    """Gemma-2: everything Gemma has plus post-attention/post-ffw norms,
    tanh soft-caps on attention and final logits, query_pre_attn_scalar
    scaling, and alternating sliding/global attention layers. The prompt
    is longer than the sliding window so the window masking is actually
    exercised against HF's implementation."""
    from transformers import Gemma2Config, Gemma2ForCausalLM
    hf = Gemma2Config(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16,
                      max_position_embeddings=128, rope_theta=10000.0,
                      query_pre_attn_scalar=32, sliding_window=6,
                      attn_logit_softcapping=50.0,
                      final_logit_softcapping=30.0,
                      attn_implementation="eager")
    torch.manual_seed(0)
    model = Gemma2ForCausalLM(hf)
    model.eval()
    path = tmp_path / "model"
    model.save_pretrained(path, safe_serialization=True)
    cfg, params = load_model_dir(str(path), dtype="float32")
    assert cfg.post_norms and cfg.attn_softcap == 50.0
    assert cfg.final_softcap == 30.0 and cfg.sliding_window == 6
    assert abs(cfg.query_scale - 32 ** -0.5) < 1e-9
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, hf.vocab_size, 12).astype(np.int32)
    ours = our_logits(cfg, params, tokens)
    theirs = hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_phi3_checkpoint_parity(tmp_path):
    """Phi-3 family: fused qkv_proj / gate_up_proj tensors split by the
    loader; otherwise llama-shaped (SiLU GLU, RMSNorm, untied head)."""
    from transformers import Phi3Config, Phi3ForCausalLM
    hf = Phi3Config(vocab_size=128, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=128,
                    rope_theta=10000.0, tie_word_embeddings=False,
                    pad_token_id=0)  # default 32000 breaks tiny vocabs
    cfg = roundtrip(tmp_path, hf, Phi3ForCausalLM)
    assert not cfg.attn_bias and cfg.mlp_act == "silu"
    assert not cfg.norm_plus_one and cfg.embed_scale == 0.0


def test_engine_serves_gemma2_greedy_parity(tmp_path):
    """Full engine decode (split-KV windows, deferred writes) must
    reproduce HF greedy generation for a Gemma-2-class model — pins the
    soft-cap / sliding-window / post-norm handling in the DECODE paths,
    not just the one-shot prefill."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams
    from dynamo_tpu.models.loader import load_params_from_hf

    hf = Gemma2Config(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=16,
                      max_position_embeddings=128, rope_theta=10000.0,
                      query_pre_attn_scalar=32, sliding_window=6,
                      attn_logit_softcapping=50.0,
                      final_logit_softcapping=30.0,
                      attn_implementation="eager")
    torch.manual_seed(3)
    model = Gemma2ForCausalLM(hf)
    model.eval()
    path = tmp_path / "ckpt"
    model.save_pretrained(path, safe_serialization=True)

    import dataclasses
    import json as _json
    with open(path / "config.json") as f:
        cfg = config_from_hf(_json.load(f))
    cfg = dataclasses.replace(cfg, dtype="float32")
    params = load_params_from_hf(str(path), cfg)
    engine = NativeEngine(cfg, EngineConfig(
        page_size=8, num_pages=32, max_slots=2, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=64, decode_steps=4),
        params=params)

    prompt = list(np.random.default_rng(2).integers(1, 256, 10))
    n_new = 12  # crosses several decode windows and the sliding boundary
    got = engine.generate([int(t) for t in prompt],
                          SamplingParams(max_tokens=n_new, temperature=0.0,
                                         ignore_eos=True), "g2")
    with torch.no_grad():
        out = model.generate(torch.tensor([prompt]), max_new_tokens=n_new,
                             do_sample=False, eos_token_id=None)
    assert got == out[0, len(prompt):].tolist()


def test_config_from_hf_rejects_unknown():
    with pytest.raises(ValueError, match="unsupported"):
        config_from_hf({"architectures": ["GPT2LMHeadModel"],
                        "num_attention_heads": 4, "vocab_size": 1,
                        "hidden_size": 4, "intermediate_size": 4,
                        "num_hidden_layers": 1})
