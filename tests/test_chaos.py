"""Chaos harness over the in-process serving graph, rebased onto the
failpoint registry (runtime/faults.py).

SURVEY.md §5 notes the reference ships NO fault-injection framework and
calls its mock network's injectable LatencyModel "the seed of one"
(reference: lib/runtime/tests/common/mock.rs:31-60). Earlier rounds grew
that seed into ad-hoc monkeypatching plus a jittery latency model; this
round replaces both with **seeded fault schedules armed on named
failpoint sites** — every scenario's fault plan is a serializable
artifact (`{site: {seed, specs}}`), the same plan replays the same
faults, and `tools/chaos_replay.py` re-runs any scenario from a recorded
plan JSON.

Each scenario is a plain function taking a plan dict (the pytest tests
run the committed default plans; the replay tool runs recorded ones) and
asserts its own contract internally:

  * liveness: nothing hangs (every phase under a hard deadline),
  * correctness: every greedy stream is token-identical to a direct
    single-engine oracle (workers share the init seed, so chaos may
    delay, MIGRATE, or re-prefill work but must never corrupt it),
  * zero drop: neither an unplanned worker death NOR a planned drain is
    ever client-visible. In-flight streams migrate — prompt + committed
    prefix re-dispatch to a survivor (resume_committed) — and continue
    with no duplicated or missing token at the boundary.

Scenarios: the aggregated jitter/abort/worker-death run, the
disaggregated (xPyD) prefill-worker death recovered by queue lease
redelivery, and the rolling restart — every worker drained and replaced
one at a time under live streaming load (the planned-maintenance leg of
the zero-drop story, docs/RESILIENCE.md runbook).
"""
import asyncio

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.frontend.reliability import (
    CircuitBreaker, ReliabilityMetrics, ReliabilityPolicy, ReliableClient,
)
from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.component import DRAIN_STATS
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8

# -- the committed fault plans -------------------------------------------------
# Every chaos scenario's faults come from one of these plan dicts: site ->
# FaultSchedule dict. Seeded delays on the transport sites reproduce the
# old JitterLatency's "jittery network" — but as a replayable artifact
# (tools/chaos_replay.py re-arms a recorded plan byte-for-byte).

AGGREGATED_PLAN = {
    "transport.send": {"seed": 11, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.02}]},
    "transport.recv": {"seed": 211, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.01}]},
}

DISAGG_PLAN = {
    "transport.send": {"seed": 23, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.01}]},
    "transport.recv": {"seed": 223, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.005}]},
    # jitter the durable-queue consumption too: dequeue delays must only
    # move work between consumers, never lose it
    "queue.dequeue": {"seed": 323, "specs": [
        {"kind": "delay", "p": 0.5, "delay_s": 0.01}]},
}

ROLLING_PLAN = {
    "transport.send": {"seed": 31, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.005}]},
    "transport.recv": {"seed": 231, "specs": [
        {"kind": "delay", "p": 1.0, "delay_s": 0.003}]},
}

# disagg transfer storm (chunk-committed data plane): seeded link cuts
# mid-stream, a deterministic 30s stall that the doomed prefill worker
# dies inside (its re-leased item must RESUME from the acked frontier),
# and queue jitter — the decode-side transfer server is also restarted
# on a new port mid-run (endpoint re-resolution), and a final leg kills
# the link for good after a majority of chunks committed (salvage).
TRANSFER_STORM_PLAN = {
    "transfer.link": {"seed": 53, "specs": [
        # the stall: hit 3 (the doomed sender's third chunk) wedges for
        # exactly 30s — the worker is killed inside it, holding a
        # part-committed transfer
        {"kind": "delay", "p": 1.0, "n": 1, "skip": 2,
         "delay_s": 30.0, "delay_min_s": 30.0},
        # seeded link cuts across the rest of the run
        {"kind": "drop", "p": 0.12}]},
    "queue.dequeue": {"seed": 353, "specs": [
        {"kind": "delay", "p": 0.5, "delay_s": 0.01}]},
    # phase E (sharded parallel streams; popped before arm_from_dict —
    # not a fault site): deterministic per-(shard, host)-stream failures
    # driven by chunk index, a pure function of these parameters
    "sharded": {"cut_stream": 1, "cut_chunk": 1,
                "dead_stream": 1, "dead_from": 2},
}

# cross-host pool service storm (ISSUE 17): host death mid-fetch,
# partition with a quorum-degraded publish, replica-local rot, and a
# host killed DURING a watch-driven rebalance — all over the replicated
# consistent-hash pool. The `pool.remote_fetch` hit index k is exactly
# the k-th LIVE replica fetch attempt of the storm (dead/partitioned
# hosts raise before the failpoint, consuming no decision), so the two
# deterministic specs pin to known attempts:
#   hits 1..4  phase A greedy walk (hit 2 = the mid-fetch host death;
#              page 1 fails over, hits 3..4 finish the walk),
#   hits 5..7  phase B sampled walk past a PARTITIONED first owner,
#   hit 8      phase C rot (corrupt -> replica-local quarantine),
#   hit 9      phase C's sibling replica serving the same page,
#   hits 10..  phase D rebalance read-side copies + the final oracle
#              re-fetch (all clean: both bounded specs are exhausted).
POOL_STORM_PLAN = {
    "pool.remote_fetch": {"seed": 61, "specs": [
        {"kind": "fail_n", "n": 1, "skip": 1},
        {"kind": "corrupt", "p": 1.0, "n": 1, "skip": 7}]},
    # kill-during-rebalance leg: seeded copy drops; repair must converge
    # anyway (idempotent passes) and no stale-epoch write may land
    "pool.rebalance": {"seed": 161, "specs": [
        {"kind": "drop", "p": 0.4}]},
    # not a fault site (popped before arm_from_dict): cluster geometry
    "pool": {"hosts": 4, "replicas": 2, "extra_entries": 12},
}

# control-plane storm (the scale-harness scenario): watch-stream
# disconnects, a discovery-store brown-out, event-plane lag/reorder, and
# seeded heartbeat loss — all at once, over a simulated fleet
CONTROL_PLANE_PLAN = {
    "watch.stream": {"seed": 41, "specs": [
        {"kind": "fail_n", "n": 2}]},
    "discovery.store": {"seed": 241, "specs": [
        {"kind": "fail_n", "n": 3},
        {"kind": "delay", "p": 0.05, "delay_s": 0.01}]},
    "event.plane": {"seed": 341, "specs": [
        {"kind": "delay", "p": 0.3, "delay_s": 0.8},
        {"kind": "drop", "p": 0.02}]},
    "discovery.heartbeat": {"seed": 441, "specs": [
        {"kind": "drop", "p": 0.05}]},
}


@pytest.fixture(autouse=True)
def disarm_after():
    yield
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()


def make_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


def pre_request(rid, prompt, max_tokens):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


def prompt_for(i):
    # ids must stay inside the tiny model's vocab (256): an OOV id NaNs
    # the embedding gather and the engine now rejects it at admission
    # (the original % 400 here was exactly such a bug — r7's all-OOV
    # prompt wrote NaN KV pages that poisoned later requests through
    # page recycling; the chaos harness caught it as cross-request
    # token corruption)
    return [(37 * i + j) % 200 + 3 for j in range(12 + (i % 3) * 4)]


_ORACLE_CACHE: dict = {}


def greedy_oracle(n, max_tokens=6):
    """Single-engine greedy oracle, cached across scenarios (engine
    seed and sampling are fixed, so the expected streams are too)."""
    missing = [i for i in range(n) if i not in _ORACLE_CACHE]
    if missing:
        eng = make_engine()
        for i in missing:
            _ORACLE_CACHE[i] = eng.generate(
                prompt_for(i), SamplingParams(max_tokens=max_tokens,
                                              temperature=0.0,
                                              ignore_eos=True), f"o{i}")
    return {i: _ORACLE_CACHE[i] for i in range(n)}


def run_scenario(name, plan=None):
    """Entry point shared with tools/chaos_replay.py: run one named
    scenario under `plan` (default: its committed plan). Raises
    AssertionError on any contract violation; returns a summary dict."""
    fn, default_plan = SCENARIOS[name]
    return fn(plan if plan is not None else default_plan)


# -- scenario: aggregated jitter + aborts + unplanned worker death -------------

def run_aggregated_zero_drop(plan):
    # oracle: same seed as both workers => identical params => identical
    # greedy tokens, independent of which worker serves — or whether the
    # stream migrated between workers mid-flight
    oracle = greedy_oracle(18)

    async def main():
        faults.REGISTRY.arm_from_dict(plan)
        plane = MemoryPlane()
        wrt1 = await DistributedRuntime.create_local(plane, "w1")
        worker1 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt1, "ns", "backend", worker1)
        wrt2 = await DistributedRuntime.create_local(plane, "w2")
        worker2 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2)

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            # stall must exceed the healthy worst-case inter-frame gap
            # (8 queued streams on 2 CPU engines can take ~1s to first
            # token); too low merely wastes a migration, never corrupts
            ReliabilityPolicy(stall_timeout_s=2.0, dispatch_timeout_s=5.0,
                              max_attempts=8, backoff_base_s=0.05,
                              backoff_max_s=0.5),
            # one stall is enough evidence mid-chaos; a long cooldown keeps
            # the dead instance ejected for the rest of the run
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics)

        async def run_request(i, abort_after=None):
            ctx = Context()
            toks = []
            async for frame in rel.generate(
                    pre_request(f"r{i}", prompt_for(i), 6), ctx):
                assert frame.get("finish_reason") != "error", (i, frame)
                toks.extend(frame.get("token_ids", ()))
                if abort_after is not None and len(toks) >= abort_after:
                    ctx.stop_generating()
                    return ("aborted", i, toks)
            return ("done", i, toks)

        # phase 1: concurrent load with jitter + mid-stream aborts
        tasks = [run_request(i, abort_after=2 if i % 4 == 3 else None)
                 for i in range(8)]
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        for r in results:
            assert not isinstance(r, BaseException), r
            kind, i, toks = r
            if kind == "done":
                assert toks == oracle[i], (i, toks, oracle[i])
            else:  # aborted streams got a correct PREFIX before stopping
                assert toks == oracle[i][:len(toks)], (i, toks)

        # phase 2: kill worker2 mid-flight — engine loop dead (streams in
        # flight there stall) AND runtime gone (lease revoked, instance
        # key pruned). ZERO client streams may error: in-flight work
        # migrates to the survivor with its committed prefix and stays
        # token-identical to the oracle (no gap, no duplicate at the
        # migration boundary).
        tasks = [asyncio.create_task(run_request(8 + i)) for i in range(5)]
        await asyncio.sleep(0.05)   # let streams start committing tokens
        await worker2.stop()
        kill = asyncio.create_task(wrt2.shutdown())
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        await kill
        for r in results:
            assert not isinstance(r, BaseException), r
            kind, i, toks = r
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])

        # phase 3: after the instance prunes, everything lands on the
        # survivor and succeeds
        for _ in range(100):
            if len(client.instances) == 1:
                break
            await asyncio.sleep(0.1)
        assert len(client.instances) == 1, client.instances
        results = await asyncio.wait_for(
            asyncio.gather(*(run_request(13 + i) for i in range(5))), 300)
        for kind, i, toks in results:
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])

        await worker1.stop()
        await crt.shutdown()
        await wrt1.shutdown()
        return metrics.snapshot()

    try:
        snap = asyncio.run(main())
    finally:
        faults.REGISTRY.disarm()
    # the kill was observed and handled by the reliability layer, not
    # absorbed by luck: something stalled/retried/migrated during phase 2
    assert snap["migrations"] + snap["retries"] >= 1, snap
    return {"reliability": snap, "faults": faults.REGISTRY.snapshot()}


def test_chaos_jitter_abort_and_worker_death_zero_drop():
    run_scenario("aggregated_zero_drop")


# -- scenario: disaggregated prefill worker death ------------------------------

def run_disagg_prefill_death(plan):
    """Disaggregated (xPyD) chaos: a prefill worker dies mid-item with a
    jittered control plane. The dequeued-but-unacked queue item's lease
    expires, it is REDELIVERED to the surviving prefill worker, and every
    client stream completes token-identical to the oracle — the decode
    side never even notices."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )

    prompts = {i: list(range(100 + 7 * i, 120 + 7 * i)) for i in range(4)}
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    oracle_engine = make_engine()
    oracle = {i: oracle_engine.generate(p, params, f"o{i}")
              for i, p in prompts.items()}

    class HoldTransfer(LocalTransferBackend):
        """Wedges every transfer: the worker using it will die mid-item."""

        async def send_pages(self, *a, **k):
            await asyncio.Event().wait()

    async def main():
        faults.REGISTRY.arm_from_dict(plan)
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=60.0)
        transfer = LocalTransferBackend()
        transfer.register("dec-0", decode)
        doomed = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, HoldTransfer(),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=0.5)
        survivor = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=5.0)
        await decode.start()
        await doomed.start()

        async def run_request(i):
            toks = []
            async for frame in decode.generate(
                    pre_request(f"r{i}", prompts[i], 6), Context(f"r{i}")):
                assert frame.get("finish_reason") not in ("error",), frame
                toks.extend(frame.get("token_ids", ()))
            return i, toks

        tasks = [asyncio.create_task(run_request(i)) for i in prompts]
        # wait until the doomed worker actually holds dequeued items, then
        # kill it mid-item: without lease/redelivery those items would be
        # gone and the streams would hang into the decode-side timeout
        deadline = asyncio.get_event_loop().time() + 30
        while not doomed._handling:
            assert asyncio.get_event_loop().time() < deadline, \
                "doomed prefill worker never picked up work"
            await asyncio.sleep(0.02)
        await doomed.stop()
        await survivor.start()

        results = await asyncio.wait_for(asyncio.gather(*tasks), 300)
        for i, toks in results:
            assert toks == oracle[i], (i, toks, oracle[i])
        redelivered = plane.messaging.redeliveries
        completed = survivor.completed
        await survivor.stop()
        await decode.stop()
        return redelivered, completed, decode.remote_prefills

    try:
        redelivered, completed, remote = asyncio.run(main())
    finally:
        faults.REGISTRY.disarm()
    assert remote == len(prompts)          # everything went remote
    assert redelivered >= 1, "no queue item was ever redelivered"
    assert completed >= 1, "survivor never completed a redelivered item"
    return {"redelivered": redelivered, "survivor_completed": completed,
            "remote_prefills": remote,
            "faults": faults.REGISTRY.snapshot()}


def test_chaos_disagg_prefill_worker_death_zero_drop():
    run_scenario("disagg_prefill_death")


# -- scenario: rolling restart of every worker under load ----------------------

def run_rolling_restart(plan):
    """Planned maintenance: every worker drained and REPLACED one at a
    time while streams run. mark_draining fences each instance out of
    new assignments (routers see status=draining), in-flight streams
    either finish within the drain deadline or are cut and MIGRATE via
    the reliability layer — zero client-visible errors, every stream
    token-identical to the undisturbed oracle."""
    oracle = greedy_oracle(12)
    drains_before = DRAIN_STATS.drains_completed

    async def main():
        faults.REGISTRY.arm_from_dict(plan)
        plane = MemoryPlane()
        fleet = {}   # tag -> (runtime, engine worker, served endpoint)

        async def spawn(tag):
            rt = await DistributedRuntime.create_local(plane, tag)
            eng = make_engine()
            # pay the jit compile BEFORE the instance registers: a cold
            # replacement stalls its first streams for the compile time,
            # which the reliability layer cannot tell from a wedged
            # worker — 12 streams migrating between two compiling
            # replacements is a retry storm, not a rolling restart
            # (real deployments warm up before readiness the same way)
            await asyncio.to_thread(
                eng.generate, prompt_for(0),
                SamplingParams(max_tokens=2, temperature=0.0,
                               ignore_eos=True), f"warmup-{tag}")
            w = await NativeEngineWorker(eng).start()
            served = await serve_llm_worker(rt, "ns", "backend", w)
            fleet[tag] = (rt, w, served)

        await spawn("w1")
        await spawn("w2")

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            # stall headroom above the healthy worst case: 12 queued
            # streams on 2 CPU engines mid-drain can legitimately gap
            # frames for seconds; too low wastes migrations (and under
            # pile-up can cascade), never corrupts
            ReliabilityPolicy(stall_timeout_s=4.0, dispatch_timeout_s=5.0,
                              max_attempts=8, backoff_base_s=0.05,
                              backoff_max_s=0.5),
            breaker=CircuitBreaker(failure_threshold=2, cooldown_s=5.0,
                                   metrics=metrics),
            metrics=metrics)

        async def run_request(i):
            toks = []
            async for frame in rel.generate(
                    pre_request(f"r{i}", prompt_for(i), 6), Context()):
                assert frame.get("finish_reason") != "error", (i, frame)
                toks.extend(frame.get("token_ids", ()))
            return i, toks

        tasks = [asyncio.create_task(run_request(i)) for i in range(12)]
        await asyncio.sleep(0.05)    # streams dispatched, some in flight

        # the rolling restart: drain + replace each original worker in
        # turn. The replacement registers BEFORE the next drain starts,
        # so capacity never reaches zero.
        for n, tag in enumerate(("w1", "w2")):
            rt, w, served = fleet.pop(tag)
            # a short deadline on the first drain forces the cut+migrate
            # leg; the second drain gets room to finish cleanly
            await served.drain(timeout_s=0.5 if n == 0 else 10.0,
                               poll_s=0.02)
            await w.stop()
            await rt.shutdown()
            await spawn(f"{tag}-replacement")

        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        for r in results:
            assert not isinstance(r, BaseException), r
            i, toks = r
            assert toks == oracle[i], (i, toks, oracle[i])

        # the fleet is whole again: both replacements serving, originals
        # gone from discovery
        for _ in range(100):
            if sorted(client.instance_ids()) == ["w1-replacement",
                                                 "w2-replacement"]:
                break
            await asyncio.sleep(0.1)
        assert sorted(client.instance_ids()) == ["w1-replacement",
                                                 "w2-replacement"], \
            client.instances

        # a fresh request on the restarted fleet still works
        i, toks = await asyncio.wait_for(run_request(11), 60)
        assert toks == oracle[11]

        for rt, w, served in fleet.values():
            await w.stop()
            await rt.shutdown()
        await crt.shutdown()
        return metrics.snapshot()

    try:
        snap = asyncio.run(main())
    finally:
        faults.REGISTRY.disarm()
    assert DRAIN_STATS.drains_completed >= drains_before + 2
    return {"reliability": snap,
            "drains": DRAIN_STATS.snapshot(),
            "faults": faults.REGISTRY.snapshot()}


def test_chaos_rolling_restart_zero_drop_token_identical():
    run_scenario("rolling_restart")


# -- scenario: disagg transfer storm (chunk-committed data plane) --------------

def run_disagg_transfer_storm(plan):
    """Mid-transfer failure storm over the REAL TCP transfer plane
    (chunk_pages=1 so every transfer is a multi-chunk stream):

      phase A — a prefill worker is killed INSIDE a transfer (the plan's
        deterministic stall) after chunks have durably committed; the
        re-leased item's replacement sender must resume from the acked
        frontier, not re-ship committed pages;
      phase B — seeded link cuts land mid-stream on the survivor; every
        cut is absorbed by reconnect+resume;
      phase C — the decode-side transfer server restarts on a NEW port
        (established connections reset, like a process restart); the
        sender must invalidate its cached endpoint and re-resolve from
        discovery;
      phase D — the link dies for good after 3 of 4 chunks committed;
        the decode worker must SALVAGE the committed prefix (local
        re-prefill only past the committed page boundary);
      phase E — SHARDED PARALLEL STREAMS (ISSUE 15): a second decode
        worker runs a ShardedKvTransferGroup (2 hosts x 2 shard
        streams); E1 cuts ONE stream once at the plan's chunk index —
        only that stream's unacked tail is re-shipped (the sibling
        stream records zero resumes); E2 kills one stream's link for
        good while the sibling completes — salvage must charge exactly
        the MIN-frontier pages (the pages EVERY stream committed).
        The per-stream failures are a pure function of the plan's
        "sharded" parameters (chunk-indexed, no randomness), so the
        committed plan replays bit-identically.

    Contract: ZERO dropped streams — every request completes
    token-identical to the aggregated oracle; >= 1 chunk-level resume is
    recorded; no request whose transfer was majority-committed is
    ever re-prefilled from token zero (salvage counters prove the
    committed prefix was reused); and the sharded phase's salvage
    charge equals the min over per-stream frontiers."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer,
        PrefillQueue, PrefillWorker, RemoteTransferBackend,
        ShardedKvTransferGroup,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.runtime.integrity import XFER_STATS

    # 30-token prompts -> 4 pages -> 4 one-page chunks per transfer
    # (8-9 feed phase E's sharded-stream legs)
    prompts = {i: [(11 * i + j) % 200 + 3 for j in range(30)]
               for i in range(10)}
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    oracle_engine = make_engine()
    oracle = {i: oracle_engine.generate(p, params, f"o{i}")
              for i, p in prompts.items()}
    r0, s0 = XFER_STATS.resumes, XFER_STATS.salvaged_pages
    plan = dict(plan)
    shp = plan.pop("sharded", {"cut_stream": 1, "cut_chunk": 1,
                               "dead_stream": 1, "dead_from": 2})

    async def main():
        faults.REGISTRY.arm_from_dict(plan)
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=32)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=90.0)
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        # window_chunks=1 keeps commits stop-and-wait: at any cut the
        # frontier equals the chunks already acked — deterministic
        doomed = PrefillWorker(
            NativeEngineWorker(make_engine()), queue,
            RemoteTransferBackend(plane.kv, chunk_pages=1,
                                  window_chunks=1),
            plane.messaging, dequeue_timeout_s=0.1, max_inflight=1,
            lease_s=0.5)
        surv_tx = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                        window_chunks=1)
        survivor = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, surv_tx,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=10.0)
        await decode.start()
        await doomed.start()

        async def run_request(i):
            from dynamo_tpu.runtime.tracing import TRACER
            ctx = Context(f"r{i}")
            # root the request's trace here (no frontend in this stack)
            # so a --trace replay captures the kv.transfer.chunk /
            # kv.transfer.resume / kv.salvage tree; None when disabled
            ctx.trace = TRACER.start_trace(f"storm-r{i}")
            toks = []
            async for frame in decode.generate(
                    pre_request(f"r{i}", prompts[i], 4), ctx):
                assert frame.get("finish_reason") not in ("error",), frame
                toks.extend(frame.get("token_ids", ()))
            return i, toks

        # phase A: kill the doomed worker inside its stalled transfer,
        # AFTER chunks have durably committed
        tasks = [asyncio.create_task(run_request(i)) for i in range(3)]
        deadline = asyncio.get_event_loop().time() + 60
        while not any(s.committed_pages >= 2
                      for s in server._sessions.values()):
            assert asyncio.get_event_loop().time() < deadline, \
                "no chunk ever committed before the kill"
            await asyncio.sleep(0.02)
        await doomed.stop()
        await survivor.start()
        results = await asyncio.wait_for(asyncio.gather(*tasks), 180)
        for i, toks in results:
            assert toks == oracle[i], (i, toks, oracle[i])
        assert plane.messaging.redeliveries >= 1, \
            "the dead sender's lease never redelivered"

        # phase B: seeded link cuts under load on the survivor
        results = await asyncio.wait_for(
            asyncio.gather(*(run_request(3 + i) for i in range(3))), 180)
        for i, toks in results:
            assert toks == oracle[i], (i, toks, oracle[i])

        # phase C: decode-side transfer server restart on a new port
        await server.stop()
        server2 = await KvTransferServer(decode, "dec-0").start()
        await server2.register(plane.kv)
        assert server2.port != server.port
        i, toks = await asyncio.wait_for(run_request(6), 180)
        assert toks == oracle[i], (i, toks, oracle[i])
        assert server2.received_pages >= 1   # re-resolved, not wedged

        # phase D: unrecoverable link after 3 of 4 chunks committed —
        # the decode side must salvage, never recompute from token zero
        faults.REGISTRY.disarm("transfer.link")
        faults.REGISTRY.arm("transfer.link", faults.FaultSchedule(
            plan["transfer.link"]["seed"],
            [faults.FaultSpec("fail_n", n=1000, skip=3)]))
        surv_tx.link_retries = 1
        i, toks = await asyncio.wait_for(run_request(7), 180)
        assert toks == oracle[i], (i, toks, oracle[i])
        faults.REGISTRY.disarm("transfer.link")
        assert decode.salvaged_prefills >= 1, "phase D never salvaged"

        # phase E: sharded parallel streams — straggler/dead SINGLE
        # stream while its sibling stays healthy. Failures are chunk-
        # indexed per stream (plan["sharded"]), so the phase is a pure
        # function of the committed plan.
        class StreamFault(RemoteTransferBackend):
            cut_done = 0
            mode = "cut"    # "cut" = once; "dead" = permanent

            async def _chunk_gate(self, chunk_idx, stream=0):
                if self.mode == "cut" \
                        and stream == shp["cut_stream"] \
                        and chunk_idx == shp["cut_chunk"] \
                        and not StreamFault.cut_done:
                    StreamFault.cut_done = 1
                    raise ConnectionResetError("seeded stream cut")
                if self.mode == "dead" \
                        and stream == shp["dead_stream"] \
                        and chunk_idx >= shp["dead_from"]:
                    raise ConnectionResetError("stream link dead")
                await super()._chunk_gate(chunk_idx, stream)

        queue_e = PrefillQueue(plane.messaging, "ns", "tiny-sharded")
        decode2 = DisaggDecodeWorker(
            make_engine(), plane.messaging, DisaggregatedRouter(
                max_local_prefill_length=4, max_prefill_queue_size=32),
            queue_e, worker_id="dec-1", prefill_timeout_s=90.0)
        group = await ShardedKvTransferGroup(
            decode2, "dec-1", hosts=2, n_streams=2).start()
        await group.register(plane.kv)
        sh_tx = StreamFault(plane.kv, chunk_pages=1, window_chunks=1,
                            link_retries=1)
        prefill_e = PrefillWorker(
            NativeEngineWorker(make_engine()), queue_e, sh_tx,
            plane.messaging, dequeue_timeout_s=0.1)
        await decode2.start()
        await prefill_e.start()
        XFER_STATS.per_stream.clear()

        async def run_request_e(i):
            ctx = Context(f"r{i}")
            toks = []
            async for frame in decode2.generate(
                    pre_request(f"r{i}", prompts[i], 4), ctx):
                assert frame.get("finish_reason") not in ("error",), frame
                toks.extend(frame.get("token_ids", ()))
            return i, toks

        # E1: one cut on one stream — resume ONLY that stream's tail
        i, toks = await asyncio.wait_for(run_request_e(8), 180)
        assert toks == oracle[i], (i, toks, oracle[i])
        snap = XFER_STATS.stream_snapshot()
        cut_key = f"dec-1/h{shp['cut_stream'] % 2}#{shp['cut_stream']}"
        sib_key = f"dec-1/h{(1 - shp['cut_stream']) % 2}" \
                  f"#{1 - shp['cut_stream']}"
        assert snap[cut_key]["resumes"] == 1, snap
        assert snap[sib_key]["resumes"] == 0, \
            "a healthy sibling stream re-shipped chunks"
        # unique per-stream accounting: 4 pages crossed each stream once
        assert snap[cut_key]["pages"] == 4 and snap[sib_key]["pages"] == 4

        # E2: one stream's link dies for good (sibling completes) —
        # salvage charges exactly the MIN over per-stream frontiers
        StreamFault.mode = "dead"
        sp0 = XFER_STATS.salvaged_pages
        i, toks = await asyncio.wait_for(run_request_e(9), 180)
        assert toks == oracle[i], (i, toks, oracle[i])
        assert decode2.salvaged_prefills == 1, "phase E2 never salvaged"
        assert XFER_STATS.salvaged_pages - sp0 == shp["dead_from"], \
            "salvage charge != min-frontier pages"
        assert decode2.majority_committed_full_reprefills == 0
        sharded_summary = {
            "stream_cut_resumes": snap[cut_key]["resumes"],
            "sibling_resumes": snap[sib_key]["resumes"],
            "salvaged_pages_e2": XFER_STATS.salvaged_pages - sp0,
            "parallel_transfers": XFER_STATS.parallel_transfers,
        }
        await prefill_e.stop()
        await decode2.stop()
        await group.stop()
        await sh_tx.close()

        # the storm-wide contracts
        assert decode.majority_committed_full_reprefills == 0, \
            "a majority-committed transfer was re-prefilled from zero"
        summary = {
            "remote_prefills": decode.remote_prefills,
            "salvaged_prefills": decode.salvaged_prefills,
            "full_reprefills": decode.full_reprefills,
            "redeliveries": plane.messaging.redeliveries,
            "resumes": XFER_STATS.resumes - r0,
            "salvaged_pages": XFER_STATS.salvaged_pages - s0,
            "sharded": sharded_summary,
        }
        await survivor.stop()
        await decode.stop()
        await server2.stop()
        return summary

    try:
        summary = asyncio.run(asyncio.wait_for(main(), 300))
    finally:
        faults.REGISTRY.disarm()
    assert summary["resumes"] >= 1, summary
    assert summary["salvaged_pages"] >= 1, summary
    summary["faults"] = faults.REGISTRY.snapshot()
    return summary


def test_chaos_disagg_transfer_storm():
    run_scenario("disagg_transfer_storm")


# -- scenario: control-plane storm over the simulated fleet --------------------

def run_pool_host_storm(plan):
    """Failure storm over the CROSS-HOST replicated KV pool
    (engine/pool_service.py + runtime/placement.py, ISSUE 17):

      phase A — a pool host "dies" serving a page mid-walk (the plan's
        deterministic drop on fetch attempt 2): the walk fails over to
        the sibling replica frontier-exact — the already-claimed page 0
        stays committed, pages 1-2 still arrive, tokens are greedy
        oracle-identical, ZERO dropped streams;
      phase B — the first ring owner of the warm prefix is PARTITIONED
        (member, unreachable — no membership change, so no rebalance):
        a seeded-SAMPLED stream fails over past it token-identically,
        and a publish whose owner set includes the partitioned host
        still lands quorum-1 on the reachable owner (counted degraded);
      phase C — bytes rot on ONE replica (the plan's corrupt on fetch
        attempt 8): that replica's verify quarantines the page LOCALLY
        and the sibling serves it — exactly one owner loses its copy;
      phase D — a new host JOINS (watch-driven handoff starts), and an
        original host is KILLED while that rebalance is mid-flight,
        under seeded rebalance-copy drops: repair passes converge
        anyway, every entry ends >= min(R, live hosts)-sourced and
        fetchable, and the stale-epoch-write counter reads ZERO (every
        copy that raced the membership change was fenced by ring epoch,
        the alloc_epoch discipline applied to placement).

    Contract: every stream token-identical to the single-engine oracle
    (greedy AND seeded-sampled), no entry lost with <= R-1 dead owners,
    `stale_epoch_landed == 0`, rot quarantined replica-locally. The
    fault plan is two bounded specs + one seeded drop rate — the run
    replays bit-identically from the committed plan."""
    import numpy as np

    from dynamo_tpu.engine.kv_cache import page_hash
    from dynamo_tpu.engine.pool_service import (
        REMOTE_STATS, RING_STATS, ClusterKvPool, KvPoolHost,
    )
    from dynamo_tpu.runtime.integrity import STATS as INTEGRITY

    plan = dict(plan)
    geo = plan.pop("pool", {"hosts": 4, "replicas": 2,
                            "extra_entries": 12})
    REMOTE_STATS.reset()
    RING_STATS.reset()
    prompt = [(13 * j) % 200 + 3 for j in range(32)]   # exactly 4 pages
    gp = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    sp = SamplingParams(max_tokens=4, temperature=0.9, top_k=8,
                        seed=1234, ignore_eos=True)
    oracle_eng = make_engine()
    want_g = oracle_eng.generate(prompt, gp, "og")
    want_s = oracle_eng.generate(prompt, sp, "os")

    def arrs(i):
        r = np.random.default_rng(i)
        shape = (2, 2, 2, 4)
        return (r.standard_normal(shape).astype(np.float32),
                r.standard_normal(shape).astype(np.float32))

    faults.REGISTRY.arm_from_dict(plan)
    try:
        cluster = ClusterKvPool(replicas=geo["replicas"])
        for i in range(geo["hosts"]):
            cluster.add_host(KvPoolHost(f"ph{i}", capacity_pages=256))
        cluster.run_rebalance()          # drain join enqueues (empty pool)
        seeder = make_engine()
        seeder.attach_kv_pool(cluster, "seed")
        seeder.generate(prompt, gp, "seed-r")
        seeder.drain_kv_events()
        seeder._pool_stream.drain()
        # the 3 matched prefix page hashes (chained content hashes)
        phashes, parent = [], 0
        for p in range(3):
            parent = page_hash(parent, prompt[p * PAGE:(p + 1) * PAGE])
            phashes.append(parent)

        # phase A: host death mid-fetch -> frontier-exact failover
        a = make_engine()
        a.attach_kv_pool(cluster, "A")
        assert a.generate(prompt, gp, "a") == want_g
        assert a.scheduler.pool_fetched_pages == 3   # no page fell back
        assert REMOTE_STATS.fetch_failovers == 1
        assert REMOTE_STATS.fetch_exhausted == 0

        # phase B: partition the warm prefix's first owner
        h0 = phashes[0]
        part = cluster.membership.owners_for(h0)[0]
        cluster.partition_host(part)
        f0 = REMOTE_STATS.fetch_failovers
        b = make_engine()
        b.attach_kv_pool(cluster, "B")
        assert b.generate(prompt, sp, "b") == want_s
        assert b.scheduler.pool_fetched_pages == 3
        assert REMOTE_STATS.fetch_failovers > f0     # walked past it
        # publisher quorum holds through the partition
        pub_sh = 0x9000
        while part not in cluster.membership.owners_for(pub_sh):
            pub_sh += 1
        assert cluster.publish("w-pub", pub_sh, 0, pub_sh,
                               arrs(pub_sh)) == "new"
        assert REMOTE_STATS.publish_quorum_degraded >= 1
        cluster.partition_host(part, False)          # heal

        # phase C: rot on one replica -> replica-local quarantine
        q0 = INTEGRITY.quarantined
        owners_before = set(cluster.owner_hosts(h0))
        assert len(owners_before) == 2
        assert cluster.fetch(h0) is not None         # sibling serves
        assert INTEGRITY.quarantined == q0 + 1
        assert len(owners_before - set(cluster.owner_hosts(h0))) == 1

        # repair the degraded publish + the rot-dropped copy before the
        # membership storm (so <= R-1 owners ever die under-repaired)
        for _ in range(40):
            if cluster.run_rebalance()["under_replicated"] == 0:
                break
        assert not cluster.under_replicated()

        # phase D: join, then kill an original host MID-rebalance
        extra = []
        for i in range(geo["extra_entries"]):
            sh = 0x5000 + i
            assert cluster.publish("w-pub", sh, 0, i, arrs(i)) == "new"
            extra.append(sh)
        cluster.add_host(KvPoolHost("ph-new", capacity_pages=256))
        cluster.run_rebalance(budget=6)              # handoff mid-flight
        victim = [h for h in cluster.membership.live_hosts()
                  if h != "ph-new"][0]
        cluster.kill_host(victim)                    # leave DURING it
        for _ in range(60):
            if cluster.run_rebalance(budget=8)["under_replicated"] == 0:
                break
        assert not cluster.under_replicated()
        target = min(geo["replicas"], len(cluster.membership.live_hosts()))
        for sh in extra + phashes + [pub_sh]:
            assert len(cluster.owner_hosts(sh)) >= target, hex(sh)
            assert cluster.fetch(sh) is not None, hex(sh)

        # the acceptance counter: NO stale-epoch write ever landed
        assert REMOTE_STATS.stale_epoch_landed == 0
        # the storm actually exercised the repair plane
        assert RING_STATS.rebalanced_pages > 0

        # epilogue: a fresh consumer over the converged cluster is
        # still greedy oracle-identical, fully pool-served
        e = make_engine()
        e.attach_kv_pool(cluster, "E")
        assert e.generate(prompt, gp, "e") == want_g
        assert e.scheduler.pool_fetched_pages == 3
        return {"remote": REMOTE_STATS.snapshot(),
                "ring": RING_STATS.snapshot(),
                "hosts": {hid: len(h)
                          for hid, h in cluster._hosts.items()},
                "faults": faults.REGISTRY.snapshot()}
    finally:
        faults.REGISTRY.disarm()
        REMOTE_STATS.reset()
        RING_STATS.reset()
        INTEGRITY.reset()


def test_chaos_pool_host_storm():
    run_scenario("pool_host_storm")


def run_control_plane_storm(plan):
    """The scale-harness scenario (runtime/simcluster.py) as a chaos
    run: a simulated fleet under watch disconnects, a discovery-store
    brown-out, event-plane lag/reorder/drop and heartbeat loss, while a
    rolling restart cycles a fleet fraction under schedule load.

    Contract: zero scheduling errors, zero post-fence picks (the router
    never selects a dead/draining worker after its watch event is
    applied), the fleet converges, and the event-lag leg must round-trip
    the router's stale-snapshot degraded mode without request errors."""
    from dynamo_tpu.runtime.cpstats import CP_STATS
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    CP_STATS.reset()

    async def main():
        sim = await SimCluster(SimConfig(
            workers=48, streams=384, seed=23, lease_ttl_s=2.0,
            scrape_interval_s=0.1, degraded_lag_s=0.5)).start()
        try:
            faults.REGISTRY.arm_from_dict(plan)
            await sim.run_load(300)
            rr = await sim.storm_rolling_restart(fraction=0.25,
                                                 load_calls=300)
            assert rr["errors"] == 0 and rr["dead_picks"] == 0, rr
            # event-plane lag (plan's delayed deliveries) must surface
            # as the degraded round trip once the armed window passes
            lag = await sim.storm_event_lag(delay_s=1.0, load_calls=150)
            faults.REGISTRY.disarm()
            assert lag["entered"] and lag["exited"], lag
            # convergence: every live worker visible, none fenced
            deadline = asyncio.get_running_loop().time() + 15
            while len(sim.client.instances) < len(sim.workers):
                assert asyncio.get_running_loop().time() < deadline, \
                    (len(sim.client.instances), len(sim.workers))
                await asyncio.sleep(0.1)
            summary = sim.summary()
            assert summary["schedule_errors"] == 0, summary
            assert summary["dead_picks"] == 0, summary
            return {"summary": summary,
                    "rolling_restart": rr, "event_lag": lag,
                    "faults": faults.REGISTRY.snapshot()}
        finally:
            faults.REGISTRY.disarm()
            await sim.stop()

    return asyncio.run(asyncio.wait_for(main(), 180))


@pytest.mark.slow
def test_chaos_control_plane_storm():
    run_scenario("control_plane_storm")


# -- scenario: fail-slow (gray failure) storm ----------------------------------

# not a fault site (popped before arm_from_dict): the A/B geometry.
# The gray failures themselves are per-worker seeded FaultSchedules
# with the persistent "slow" kind, built inside
# SimCluster.fail_slow_ab from this geometry — one schedule per
# degraded worker, so the same plan replays the same sick fleet.
FAILSLOW_PLAN = {
    "failslow": {"workers": 32, "requests": 1500, "seed": 7,
                 "min_p99_margin": 0.25},
}


def run_fail_slow_storm(plan):
    """Gray-failure storm (docs/RESILIENCE.md "Fail-slow failure
    model"): a seeded fraction of a simulated fleet degrades through
    the persistent ``slow`` fault kind — alive, answering, dragging
    p99 — and the detection plane (HealthScorer + SLOW dispatch share
    + hedged dispatch) runs A/B against a detection-blind baseline
    over the identical seeded request stream.

    Four contracts, all hard-asserted:
      1. p99 TTFT with detection ON beats OFF by the plan's margin;
      2. zero dropped streams in BOTH modes (hedging never loses a
         first token; pre-commit-only hedges cannot double-commit);
      3. zero false ejections — no healthy worker is ever marked SLOW
         (the min-evidence floor + MAD robustness);
      4. the SLOW decision timeline replays bit-identically (two
         same-seed ON runs produce byte-equal timelines)."""
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    plan = dict(plan)
    geo = dict(plan.pop("failslow", {}))
    workers = int(geo.get("workers", 32))
    requests = int(geo.get("requests", 1500))
    seed = int(geo.get("seed", 7))
    min_margin = float(geo.get("min_p99_margin", 0.25))

    async def main():
        faults.REGISTRY.arm_from_dict(plan)
        # mock-only fleet: fail_slow_ab is a pure virtual-time model
        # over the worker id set, so the control plane never starts
        sim = SimCluster(SimConfig(workers=workers, seed=seed))
        sim.workers = {f"w{i:04d}": None for i in range(workers)}
        try:
            return await sim.fail_slow_ab(requests=requests)
        finally:
            faults.REGISTRY.disarm()

    rep = asyncio.run(asyncio.wait_for(main(), 300))
    on, off = rep["detection_on"], rep["detection_off"]
    # contract 1: the detection plane earns its keep at the tail
    assert rep["p99_improvement"] >= min_margin, (
        rep["p99_improvement"], min_margin)
    # contract 2: no stream ever lost its first token, either mode
    assert on["dropped"] == 0 and off["dropped"] == 0, (on, off)
    # contract 3: zero false ejections of healthy workers
    assert on["false_ejections"] == [], on["false_ejections"]
    # contract 4: bit-identical decision-timeline replay
    assert rep["timeline_replay_ok"], "SLOW timeline diverged on replay"
    # the machinery demonstrably fired: gray workers were detected and
    # hedges dispatched (a storm where nothing happens proves nothing)
    assert rep["degraded_workers"] >= 1, rep
    assert on["detected_slow"], rep
    assert on["hedges_fired"] >= 1, on
    # keep the committed artifact light: the timelines are replay-
    # verified above, only the ON timeline (the decision record) ships
    trimmed = dict(rep)
    trimmed["detection_on"] = dict(on)
    trimmed["detection_off"] = {k: v for k, v in off.items()
                                if k != "timeline"}
    return trimmed


def test_chaos_fail_slow_storm():
    run_scenario("fail_slow_storm")


@pytest.mark.slow
def test_chaos_fail_slow_storm_1000_workers():
    rep = run_scenario("fail_slow_storm", {
        "failslow": {"workers": 1000, "requests": 40000, "seed": 7,
                     "min_p99_margin": 0.30}})
    # at scale the detector must catch a substantial share of the sick
    on = rep["detection_on"]
    assert len(on["detected_slow"]) >= rep["degraded_workers"] // 2, rep


# -- hedged dispatch: token identity on real engines ---------------------------
#
# ISSUE 19 acceptance: a hedged request is TOKEN-IDENTICAL to an
# unhedged single-engine run — greedy AND seeded-sampled — on both the
# aggregated and the disaggregated serving path. The mechanism is
# pre-commit-only first-frame-wins racing (frontend/reliability.py):
# the losing attempt is cancelled with zero tokens committed, so the
# winner's stream is indistinguishable from a lone dispatch. These
# tests force a hedge on EVERY request (zero hedge delay, generous
# budget) and compare against direct single-engine oracles.

def _hedge_policy():
    return ReliabilityPolicy(
        hedge_enabled=True, hedge_min_delay_s=0.0, hedge_max_delay_s=0.01,
        hedge_budget_frac=1.0, hedge_burst=64,
        stall_timeout_s=5.0, dispatch_timeout_s=10.0, max_attempts=6,
        backoff_base_s=0.05)


def sampled_request(rid, prompt, max_tokens, seed):
    from dynamo_tpu.protocols.common import SamplingOptions
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        sampling=SamplingOptions(temperature=0.8, top_k=40, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


def _sampled_params(seed, max_tokens=6):
    return SamplingParams(max_tokens=max_tokens, temperature=0.8,
                          top_k=40, seed=seed, ignore_eos=True)


async def _collect(rel, request, rid):
    toks = []
    async for frame in rel.generate(request, Context(rid)):
        assert frame.get("finish_reason") != "error", (rid, frame)
        toks.extend(frame.get("token_ids", ()))
    return toks


def test_hedged_streams_token_identical_aggregated():
    """Every request hedges across two same-seed workers; greedy and
    seeded-sampled streams both match the unhedged single-engine
    oracle token for token, whichever attempt won its race."""
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    oracle = greedy_oracle(4)
    eng = make_engine()
    sampled_oracle = {i: eng.generate(prompt_for(i), _sampled_params(500 + i),
                                      f"so{i}")
                      for i in range(4)}

    async def main():
        plane = MemoryPlane()
        wrt1 = await DistributedRuntime.create_local(plane, "w1")
        worker1 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt1, "ns", "backend", worker1)
        wrt2 = await DistributedRuntime.create_local(plane, "w2")
        worker2 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        for _ in range(200):
            if len(client.instances) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(client.instances) == 2, client.instances

        HEDGE_STATS.reset()
        rel = ReliableClient(client, _hedge_policy(),
                             health=HealthScorer())
        try:
            for i in range(4):
                toks = await _collect(
                    rel, pre_request(f"hg{i}", prompt_for(i), 6), f"hg{i}")
                assert toks == oracle[i], (i, toks, oracle[i])
            for i in range(4):
                toks = await _collect(
                    rel, sampled_request(f"hs{i}", prompt_for(i), 6,
                                         500 + i), f"hs{i}")
                assert toks == sampled_oracle[i], (
                    i, toks, sampled_oracle[i])
        finally:
            await worker1.stop()
            await worker2.stop()
            for rt in (crt, wrt1, wrt2):
                await rt.shutdown()
        return HEDGE_STATS.snapshot()

    snap = asyncio.run(asyncio.wait_for(main(), 300))
    # the races actually happened, and each settled exactly once
    assert snap["fired"] >= 4, snap
    assert snap["wins"] + snap["losses"] == snap["fired"], snap


def test_hedged_streams_token_identical_disagg():
    """The same exactness contract on the disaggregated path: hedges
    race across two decode workers, each driving its own remote
    prefill through the shared queue — the loser's prefill is wasted
    work, never wrong tokens."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.runtime.health import HEDGE_STATS, HealthScorer

    oracle = greedy_oracle(3)
    eng = make_engine()
    sampled_oracle = {i: eng.generate(prompt_for(i), _sampled_params(700 + i),
                                      f"do{i}")
                      for i in range(3)}

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=32)
        transfer = LocalTransferBackend()
        decodes, rts = [], []
        for i in range(2):
            dec = DisaggDecodeWorker(
                make_engine(), plane.messaging, router, queue,
                worker_id=f"dec-{i}", prefill_timeout_s=60.0)
            transfer.register(f"dec-{i}", dec)
            await dec.start()
            decodes.append(dec)
            rt = await DistributedRuntime.create_local(plane, f"d{i}")
            await serve_llm_worker(rt, "ns", "decode", dec)
            rts.append(rt)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=5.0)
        await prefill.start()
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("decode").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        for _ in range(200):
            if len(client.instances) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(client.instances) == 2, client.instances

        HEDGE_STATS.reset()
        rel = ReliableClient(client, _hedge_policy(),
                             health=HealthScorer())
        try:
            for i in range(3):
                toks = await _collect(
                    rel, pre_request(f"dg{i}", prompt_for(i), 6), f"dg{i}")
                assert toks == oracle[i], (i, toks, oracle[i])
            for i in range(3):
                toks = await _collect(
                    rel, sampled_request(f"ds{i}", prompt_for(i), 6,
                                         700 + i), f"ds{i}")
                assert toks == sampled_oracle[i], (
                    i, toks, sampled_oracle[i])
            remote = sum(d.remote_prefills for d in decodes)
        finally:
            await prefill.stop()
            for d in decodes:
                await d.stop()
            for rt in rts + [crt]:
                await rt.shutdown()
        return HEDGE_STATS.snapshot(), remote

    snap, remote = asyncio.run(asyncio.wait_for(main(), 300))
    assert snap["fired"] >= 3, snap
    assert snap["wins"] + snap["losses"] == snap["fired"], snap
    assert remote >= 1, "nothing ever took the remote prefill path"


# name -> (runner, committed default plan); tools/chaos_replay.py's menu
SCENARIOS = {
    "aggregated_zero_drop": (run_aggregated_zero_drop, AGGREGATED_PLAN),
    "disagg_prefill_death": (run_disagg_prefill_death, DISAGG_PLAN),
    "disagg_transfer_storm": (run_disagg_transfer_storm,
                              TRANSFER_STORM_PLAN),
    "rolling_restart": (run_rolling_restart, ROLLING_PLAN),
    "control_plane_storm": (run_control_plane_storm, CONTROL_PLANE_PLAN),
    "pool_host_storm": (run_pool_host_storm, POOL_STORM_PLAN),
    "fail_slow_storm": (run_fail_slow_storm, FAILSLOW_PLAN),
}
