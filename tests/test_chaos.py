"""Fault-injection (chaos) harness over the in-process serving graph.

SURVEY.md §5 notes the reference ships NO fault-injection framework and
calls its mock network's injectable LatencyModel "the seed of one"
(reference: lib/runtime/tests/common/mock.rs:31-60). This harness grows
that seed: a seeded random-jitter latency model on EVERY control-plane op
(KV, watch, messaging), a real router+workers serving graph behind the
reliability layer (frontend/reliability.py), concurrent streams,
mid-stream client aborts, and mid-run worker deaths — asserting

  * liveness: nothing hangs (every phase under a hard deadline),
  * correctness: every greedy stream is token-identical to a direct
    single-engine oracle (both workers share the init seed, so chaos may
    delay or MIGRATE work but must never corrupt it),
  * zero drop: a worker death is never client-visible. Streams in flight
    on the killed worker migrate — prompt + committed prefix re-dispatch
    to the survivor (PreprocessedRequest.resume_committed) — and continue
    with no duplicated or missing token at the migration boundary. This
    upgrades the original harness's contract ("only streams on the killed
    worker may error") to "no stream errors, ever".

The disaggregated (xPyD) graph gets its own seeded chaos test below:
a prefill worker killed mid-item, recovered by the prefill queue's
lease/redelivery (disagg/queue.py).
"""
import asyncio
import random

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.frontend.reliability import (
    CircuitBreaker, ReliabilityMetrics, ReliabilityPolicy, ReliableClient,
)
from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import LatencyModel, MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


class JitterLatency(LatencyModel):
    """Seeded random delay per control-plane op — turns the in-memory
    plane into a jittery 'network' that reorders interleavings."""

    def __init__(self, seed: int, max_delay_s: float):
        super().__init__(0.0)
        self._rng = random.Random(seed)
        self.max_delay_s = max_delay_s

    async def apply(self):
        await asyncio.sleep(self._rng.random() * self.max_delay_s)


def pre_request(rid, prompt, max_tokens):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


def prompt_for(i):
    # ids must stay inside the tiny model's vocab (256): an OOV id NaNs
    # the embedding gather and the engine now rejects it at admission
    # (the original % 400 here was exactly such a bug — r7's all-OOV
    # prompt wrote NaN KV pages that poisoned later requests through
    # page recycling; the chaos harness caught it as cross-request
    # token corruption)
    return [(37 * i + j) % 200 + 3 for j in range(12 + (i % 3) * 4)]


def test_chaos_jitter_abort_and_worker_death_zero_drop():
    # oracle: same seed as both workers => identical params => identical
    # greedy tokens, independent of which worker serves — or whether the
    # stream migrated between workers mid-flight
    oracle_engine = make_engine()
    oracle = {}
    for i in range(18):
        oracle[i] = oracle_engine.generate(
            prompt_for(i), SamplingParams(max_tokens=6, temperature=0.0,
                                          ignore_eos=True), f"o{i}")

    async def main():
        plane = MemoryPlane(JitterLatency(seed=11, max_delay_s=0.02))
        wrt1 = await DistributedRuntime.create_local(plane, "w1")
        worker1 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt1, "ns", "backend", worker1)
        wrt2 = await DistributedRuntime.create_local(plane, "w2")
        worker2 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2)

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            # stall must exceed the healthy worst-case inter-frame gap
            # (8 queued streams on 2 CPU engines can take ~1s to first
            # token); too low merely wastes a migration, never corrupts
            ReliabilityPolicy(stall_timeout_s=2.0, dispatch_timeout_s=5.0,
                              max_attempts=8, backoff_base_s=0.05,
                              backoff_max_s=0.5),
            # one stall is enough evidence mid-chaos; a long cooldown keeps
            # the dead instance ejected for the rest of the run
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics)

        async def run_request(i, abort_after=None):
            ctx = Context()
            toks = []
            async for frame in rel.generate(
                    pre_request(f"r{i}", prompt_for(i), 6), ctx):
                assert frame.get("finish_reason") != "error", (i, frame)
                toks.extend(frame.get("token_ids", ()))
                if abort_after is not None and len(toks) >= abort_after:
                    ctx.stop_generating()
                    return ("aborted", i, toks)
            return ("done", i, toks)

        # phase 1: concurrent load with jitter + mid-stream aborts
        tasks = [run_request(i, abort_after=2 if i % 4 == 3 else None)
                 for i in range(8)]
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        for r in results:
            assert not isinstance(r, BaseException), r
            kind, i, toks = r
            if kind == "done":
                assert toks == oracle[i], (i, toks, oracle[i])
            else:  # aborted streams got a correct PREFIX before stopping
                assert toks == oracle[i][:len(toks)], (i, toks)

        # phase 2: kill worker2 mid-flight — engine loop dead (streams in
        # flight there stall) AND runtime gone (lease revoked, instance
        # key pruned). ZERO client streams may error: in-flight work
        # migrates to the survivor with its committed prefix and stays
        # token-identical to the oracle (no gap, no duplicate at the
        # migration boundary).
        tasks = [asyncio.create_task(run_request(8 + i)) for i in range(5)]
        await asyncio.sleep(0.05)   # let streams start committing tokens
        await worker2.stop()
        kill = asyncio.create_task(wrt2.shutdown())
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        await kill
        for r in results:
            assert not isinstance(r, BaseException), r
            kind, i, toks = r
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])

        # phase 3: after the instance prunes, everything lands on the
        # survivor and succeeds
        for _ in range(100):
            if len(client.instances) == 1:
                break
            await asyncio.sleep(0.1)
        assert len(client.instances) == 1, client.instances
        results = await asyncio.wait_for(
            asyncio.gather(*(run_request(13 + i) for i in range(5))), 300)
        for kind, i, toks in results:
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])

        await worker1.stop()
        await crt.shutdown()
        await wrt1.shutdown()
        return metrics.snapshot()

    snap = asyncio.run(main())
    # the kill was observed and handled by the reliability layer, not
    # absorbed by luck: something stalled/retried/migrated during phase 2
    assert snap["migrations"] + snap["retries"] >= 1, snap


def test_chaos_disagg_prefill_worker_death_zero_drop():
    """Disaggregated (xPyD) chaos: a prefill worker dies mid-item with
    jittered control plane. The dequeued-but-unacked queue item's lease
    expires, it is REDELIVERED to the surviving prefill worker, and every
    client stream completes token-identical to the oracle — the decode
    side never even notices."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )

    prompts = {i: list(range(100 + 7 * i, 120 + 7 * i)) for i in range(4)}
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    oracle_engine = make_engine()
    oracle = {i: oracle_engine.generate(p, params, f"o{i}")
              for i, p in prompts.items()}

    class HoldTransfer(LocalTransferBackend):
        """Wedges every transfer: the worker using it will die mid-item."""

        async def send_pages(self, *a, **k):
            await asyncio.Event().wait()

    async def main():
        plane = MemoryPlane(JitterLatency(seed=23, max_delay_s=0.01))
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=60.0)
        transfer = LocalTransferBackend()
        transfer.register("dec-0", decode)
        doomed = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, HoldTransfer(),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=0.5)
        survivor = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=5.0)
        await decode.start()
        await doomed.start()

        async def run_request(i):
            toks = []
            async for frame in decode.generate(
                    pre_request(f"r{i}", prompts[i], 6), Context(f"r{i}")):
                assert frame.get("finish_reason") not in ("error",), frame
                toks.extend(frame.get("token_ids", ()))
            return i, toks

        tasks = [asyncio.create_task(run_request(i)) for i in prompts]
        # wait until the doomed worker actually holds dequeued items, then
        # kill it mid-item: without lease/redelivery those items would be
        # gone and the streams would hang into the decode-side timeout
        deadline = asyncio.get_event_loop().time() + 30
        while not doomed._handling:
            assert asyncio.get_event_loop().time() < deadline, \
                "doomed prefill worker never picked up work"
            await asyncio.sleep(0.02)
        await doomed.stop()
        await survivor.start()

        results = await asyncio.wait_for(asyncio.gather(*tasks), 300)
        for i, toks in results:
            assert toks == oracle[i], (i, toks, oracle[i])
        redelivered = plane.messaging.redeliveries
        completed = survivor.completed
        await survivor.stop()
        await decode.stop()
        return redelivered, completed, decode.remote_prefills

    redelivered, completed, remote = asyncio.run(main())
    assert remote == len(prompts)          # everything went remote
    assert redelivered >= 1, "no queue item was ever redelivered"
    assert completed >= 1, "survivor never completed a redelivered item"
