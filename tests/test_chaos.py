"""Fault-injection (chaos) harness over the in-process serving graph.

SURVEY.md §5 notes the reference ships NO fault-injection framework and
calls its mock network's injectable LatencyModel "the seed of one"
(reference: lib/runtime/tests/common/mock.rs:31-60). This grows that
seed into a harness: a seeded random-jitter latency model on EVERY
control-plane op (KV, watch, messaging), a real router+workers serving
graph, concurrent streams, mid-stream client aborts, and a mid-run
worker death — asserting

  * liveness: nothing hangs (every phase under a hard deadline),
  * correctness: every COMPLETED greedy stream is token-identical to a
    direct single-engine oracle (both workers share the init seed, so
    chaos may delay or kill work but must never corrupt it),
  * clean failure + recovery: only streams in flight on the killed
    worker may error, and once its lease-scoped instance key is pruned,
    new requests all land on the survivor and succeed.
"""
import asyncio
import random

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import LatencyModel, MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


class JitterLatency(LatencyModel):
    """Seeded random delay per control-plane op — turns the in-memory
    plane into a jittery 'network' that reorders interleavings."""

    def __init__(self, seed: int, max_delay_s: float):
        super().__init__(0.0)
        self._rng = random.Random(seed)
        self.max_delay_s = max_delay_s

    async def apply(self):
        await asyncio.sleep(self._rng.random() * self.max_delay_s)


def pre_request(rid, prompt, max_tokens):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


def prompt_for(i):
    # ids must stay inside the tiny model's vocab (256): an OOV id NaNs
    # the embedding gather and the engine now rejects it at admission
    # (the original % 400 here was exactly such a bug — r7's all-OOV
    # prompt wrote NaN KV pages that poisoned later requests through
    # page recycling; the chaos harness caught it as cross-request
    # token corruption)
    return [(37 * i + j) % 200 + 3 for j in range(12 + (i % 3) * 4)]


def test_chaos_jitter_abort_and_worker_death():
    # oracle: same seed as both workers => identical params => identical
    # greedy tokens, independent of which worker serves
    oracle_engine = make_engine()
    oracle = {}
    for i in range(18):
        oracle[i] = oracle_engine.generate(
            prompt_for(i), SamplingParams(max_tokens=6, temperature=0.0,
                                          ignore_eos=True), f"o{i}")

    async def main():
        plane = MemoryPlane(JitterLatency(seed=11, max_delay_s=0.02))
        wrt1 = await DistributedRuntime.create_local(plane, "w1")
        worker1 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt1, "ns", "backend", worker1)
        wrt2 = await DistributedRuntime.create_local(plane, "w2")
        worker2 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2)

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        async def run_request(i, abort_after=None):
            ctx = Context()
            toks = []
            async for frame in await client.generate(
                    pre_request(f"r{i}", prompt_for(i), 6), ctx):
                toks.extend(frame.get("token_ids", ()))
                if abort_after is not None and len(toks) >= abort_after:
                    ctx.stop_generating()
                    return ("aborted", i, toks)
            return ("done", i, toks)

        # phase 1: concurrent load with jitter + mid-stream aborts
        tasks = [run_request(i, abort_after=2 if i % 4 == 3 else None)
                 for i in range(8)]
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        for r in results:
            assert not isinstance(r, BaseException), r
            kind, i, toks = r
            if kind == "done":
                assert toks == oracle[i], (i, toks, oracle[i])
            else:  # aborted streams got a correct PREFIX before stopping
                assert toks == oracle[i][:len(toks)], (i, toks)

        # phase 2: kill worker2's runtime mid-flight (lease revoked,
        # instance key gone — the crash-equivalent for the routing layer)
        tasks = [run_request(8 + i) for i in range(5)]
        kill = asyncio.create_task(wrt2.shutdown())
        results = await asyncio.wait_for(
            asyncio.gather(*tasks, return_exceptions=True), 300)
        await kill
        failed_ids = []
        for idx, r in enumerate(results):
            if isinstance(r, BaseException):
                failed_ids.append(8 + idx)  # in flight on the dying worker
                continue
            kind, i, toks = r
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])
        # the healthy worker must keep serving THROUGH the kill: a dying
        # peer may fail its own in-flight streams but must never take the
        # whole component down
        assert len(failed_ids) < len(results), \
            "every request failed during the kill"
        # and every failure must be TRANSIENT (tied to the dying
        # instance): an immediate retry, bounded by the prune window, must
        # succeed with oracle-exact tokens — a systemic error (healthy
        # worker corrupted, router broken) would fail retries too
        loop = asyncio.get_event_loop()
        for i in failed_ids:
            deadline = loop.time() + 60
            while True:
                try:
                    # bounded await: a retried stream that HANGS (rather
                    # than erroring) must trip the deadline too, not
                    # stall the harness past its own liveness invariant
                    kind, _, toks = await asyncio.wait_for(
                        run_request(i), max(1.0, deadline - loop.time()))
                    assert kind == "done" and toks == oracle[i], (i, toks)
                    break
                except AssertionError:
                    raise
                except Exception:
                    if loop.time() > deadline:
                        raise
                    await asyncio.sleep(0.5)

        # phase 3: after the instance prunes, everything lands on the
        # survivor and succeeds
        for _ in range(100):
            if len(client.instances) == 1:
                break
            await asyncio.sleep(0.1)
        assert len(client.instances) == 1, client.instances
        results = await asyncio.wait_for(
            asyncio.gather(*(run_request(13 + i) for i in range(5))), 300)
        for kind, i, toks in results:
            assert kind == "done"
            assert toks == oracle[i], (i, toks, oracle[i])

        await worker1.stop()
        await worker2.stop()
        await crt.shutdown()
        await wrt1.shutdown()

    asyncio.run(main())
