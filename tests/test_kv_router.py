"""KV-aware router tests.

Mirrors the reference's indexer/scheduler unit tests (SURVEY.md §4.1:
lib/llm/src/kv_router/indexer.rs:900-1409) plus an end-to-end router test
over the in-memory control plane: engine allocator events -> publisher ->
indexer -> scheduler -> worker choice.
"""
import asyncio
import random

import pytest

from dynamo_tpu.engine.kv_cache import PageAllocator, page_hash, tokens_hash
from dynamo_tpu.kv_router.indexer import KvIndexer, KvIndexerSharded, RadixTree
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheRemoveData, KvCacheStoreData, KvCacheStoredBlockData,
    RouterEvent, compute_page_hashes,
)
from dynamo_tpu.kv_router.publisher import (
    KvEventPublisher, KvMetricsAggregator, KvMetricsPublisher,
)
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.scheduler import (
    AllWorkersBusy, DefaultWorkerSelector, KvScheduler,
)
from dynamo_tpu.kv_router.scoring import ProcessedEndpoints, WorkerMetrics
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane


def stored(worker, seq, parent=None, eid=0):
    """Build a Stored RouterEvent for a chain of (block_hash, tokens_hash)."""
    return RouterEvent(worker, KvCacheEvent(eid, KvCacheStoreData(
        parent_hash=parent,
        blocks=[KvCacheStoredBlockData(bh, th) for bh, th in seq])))


def removed(worker, hashes, eid=0):
    return RouterEvent(worker, KvCacheEvent(
        eid, KvCacheRemoveData(list(hashes))))


class TestRadixTree:
    def test_store_and_match(self):
        tree = RadixTree()
        # w1 holds pages [A, B]; w2 holds [A]
        tree.apply_event(stored("w1", [(101, 1), (102, 2)]))
        tree.apply_event(stored("w2", [(201, 1)]))
        res = tree.find_matches([1, 2, 3])
        assert res.scores == {"w1": 2, "w2": 1}
        # divergent first page: nothing
        assert tree.find_matches([9]).scores == {}

    def test_chained_store_via_parent(self):
        tree = RadixTree()
        tree.apply_event(stored("w1", [(101, 1)]))
        # extend from parent block_hash 101
        tree.apply_event(stored("w1", [(102, 2)], parent=101))
        assert tree.find_matches([1, 2]).scores == {"w1": 2}

    def test_removed_prunes(self):
        tree = RadixTree()
        tree.apply_event(stored("w1", [(101, 1), (102, 2)]))
        tree.apply_event(removed("w1", [102]))
        assert tree.find_matches([1, 2]).scores == {"w1": 1}
        assert tree.num_nodes() == 1  # leaf pruned
        tree.apply_event(removed("w1", [101]))
        assert tree.find_matches([1]).scores == {}
        assert tree.num_nodes() == 0

    def test_removal_keeps_shared_node(self):
        tree = RadixTree()
        tree.apply_event(stored("w1", [(101, 1)]))
        tree.apply_event(stored("w2", [(201, 1)]))
        tree.apply_event(removed("w1", [101]))
        assert tree.find_matches([1]).scores == {"w2": 1}

    def test_remove_worker(self):
        tree = RadixTree()
        tree.apply_event(stored("w1", [(101, 1), (102, 2)]))
        tree.apply_event(stored("w2", [(201, 1)]))
        tree.remove_worker("w1")
        assert tree.find_matches([1, 2]).scores == {"w2": 1}
        assert tree.worker_block_count("w1") == 0
        # interior node with a child must survive even with no workers
        tree.apply_event(stored("w3", [(301, 1), (302, 2), (303, 3)]))
        tree.remove_worker("w2")
        assert tree.find_matches([1, 2, 3]).scores == {"w3": 3}

    def test_frequency_tracking_expiry(self):
        tree = RadixTree(expiration_duration_s=10.0)
        tree.apply_event(stored("w1", [(101, 1)]))
        r1 = tree.find_matches([1], now=0.0)
        r2 = tree.find_matches([1], now=1.0)
        assert r1.frequencies == [1] and r2.frequencies == [2]
        r3 = tree.find_matches([1], now=100.0)  # both expired
        assert r3.frequencies == [1]

    def test_event_roundtrip_pack_unpack(self):
        ev = stored("w1", [(101, 1), (102, 2)], parent=5, eid=7)
        assert RouterEvent.unpack(ev.pack()) == ev
        ev2 = removed("w9", [11, 12], eid=8)
        assert RouterEvent.unpack(ev2.pack()) == ev2


class TestIndexers:
    def test_indexer_token_query(self):
        idx = KvIndexer(block_size=4)
        toks = list(range(12))
        h = compute_page_hashes(toks, 4)
        idx.apply_event(stored("w1", [(1, h[0]), (2, h[1]), (3, h[2])]))
        res = idx.find_matches_for_tokens(toks + [99, 100])  # partial page ignored
        assert res.scores == {"w1": 3}
        # only first page matches
        res2 = idx.find_matches_for_tokens(toks[:4] + [7, 7, 7, 7])
        assert res2.scores == {"w1": 1}

    def test_sharded_matches_unsharded(self):
        idx = KvIndexer(block_size=2)
        sharded = KvIndexerSharded(block_size=2, num_shards=3)
        rng = random.Random(0)
        workers = [f"w{i}" for i in range(8)]
        for eid in range(200):
            w = rng.choice(workers)
            chain = [(rng.randrange(1 << 30), rng.randrange(8))
                     for _ in range(rng.randrange(1, 4))]
            ev = stored(w, chain, eid=eid)
            idx.apply_event(ev)
            sharded.apply_event(ev)
        for _ in range(50):
            q = [rng.randrange(8) for _ in range(rng.randrange(1, 5))]
            assert idx.find_matches(q).scores == sharded.find_matches(q).scores
        sharded.remove_worker("w3")
        idx.remove_worker("w3")
        for _ in range(20):
            q = [rng.randrange(8) for _ in range(3)]
            assert idx.find_matches(q).scores == sharded.find_matches(q).scores


class TestScheduler:
    def _endpoints(self, **workers):
        return ProcessedEndpoints({
            wid: WorkerMetrics(**kw) for wid, kw in workers.items()})

    def test_overlap_wins(self):
        sched = KvScheduler(block_size=16,
                            selector=DefaultWorkerSelector(rng=random.Random(0)))
        sched.update_endpoints(self._endpoints(
            w1=dict(request_active_slots=1, request_total_slots=8,
                    kv_active_blocks=10, kv_total_blocks=100),
            w2=dict(request_active_slots=1, request_total_slots=8,
                    kv_active_blocks=10, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        overlap = MatchResult(scores={"w2": 4})  # w2 holds 4 of 4 pages
        assert sched.schedule(64, overlap) == "w2"
        ev = sched.drain_hit_events()
        assert len(ev) == 1 and ev[0].overlap_blocks == 4

    def test_load_breaks_even_overlap(self):
        sched = KvScheduler(block_size=16,
                            selector=DefaultWorkerSelector(rng=random.Random(0)))
        sched.update_endpoints(self._endpoints(
            busy=dict(request_active_slots=8, request_total_slots=8,
                      kv_active_blocks=90, kv_total_blocks=100),
            idle=dict(request_active_slots=0, request_total_slots=8,
                      kv_active_blocks=5, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        assert sched.schedule(64, MatchResult()) == "idle"

    def test_optimistic_bump(self):
        sched = KvScheduler(block_size=16,
                            selector=DefaultWorkerSelector(rng=random.Random(0)))
        sched.update_endpoints(self._endpoints(
            w1=dict(request_total_slots=8, kv_total_blocks=100),
            w2=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        picks = {sched.schedule(160, MatchResult()) for _ in range(2)}
        # after the first pick its slots/blocks were bumped -> second differs
        assert picks == {"w1", "w2"}

    def test_no_workers_raises(self):
        sched = KvScheduler(block_size=16)
        from dynamo_tpu.kv_router.indexer import MatchResult
        with pytest.raises(AllWorkersBusy):
            sched.schedule(10, MatchResult())


class TestTransferAwareSelector:
    """Transfer-aware scoring (ROADMAP item 3 / ISSUE 11): estimated
    KV-transfer cost folds into the logit next to overlap and load."""

    def _endpoints(self, **workers):
        return ProcessedEndpoints({
            wid: WorkerMetrics(**kw) for wid, kw in workers.items()})

    def _model(self, **bw):
        from dynamo_tpu.observability.fleet import TransferCostModel
        m = TransferCostModel()
        for link, bytes_per_s in bw.items():
            m.observe(link, int(bytes_per_s), 1.0)
        return m

    def _selector(self, model, **kw):
        from dynamo_tpu.kv_router.scheduler import TransferAwareSelector
        kw.setdefault("rng", random.Random(0))
        kw.setdefault("default_block_bytes", 1 << 20)   # 1 MiB/block
        return TransferAwareSelector(cost_model=model, **kw)

    def test_slow_link_loses_at_equal_overlap_and_load(self):
        # identical load, no overlap anywhere: the only signal is the
        # measured link bandwidth — the fast link must win
        model = self._model(fast=1 << 30, slow=1 << 22)   # 1 GiB/s vs 4 MiB/s
        sched = KvScheduler(block_size=16,
                            selector=self._selector(model))
        sched.update_endpoints(self._endpoints(
            fast=dict(request_total_slots=8, kv_total_blocks=100),
            slow=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        assert sched.schedule(160, MatchResult()) == "fast"
        comps = sched.selector.last_components
        assert comps["slow"]["transfer_s"] > comps["fast"]["transfer_s"]
        assert not comps["fast"]["cold"] and not comps["slow"]["cold"]

    def test_overlap_shrinks_bytes_to_move(self):
        # a warm worker ships fewer bytes: overlap reduces the cost term
        # (and wins) even on an equal-speed link
        model = self._model(warm=1 << 28, cold_w=1 << 28)
        sched = KvScheduler(block_size=16,
                            selector=self._selector(model))
        sched.update_endpoints(self._endpoints(
            warm=dict(request_total_slots=8, kv_total_blocks=100),
            cold_w=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        assert sched.schedule(160, MatchResult(scores={"warm": 8})) == "warm"
        comps = sched.selector.last_components
        assert comps["warm"]["transfer_bytes"] \
            < comps["cold_w"]["transfer_bytes"]

    def test_cold_link_neither_free_nor_infinite(self):
        # satellite pin: a never-measured link prices at the fleet
        # median — its cost term is strictly positive AND finite, and
        # the decision is flagged cold
        from dynamo_tpu.kv_router.stats import ROUTER_STATS
        ROUTER_STATS.reset()
        model = self._model(measured=1 << 24)    # 16 MiB/s fleet median
        sel = self._selector(model)
        sched = KvScheduler(block_size=16, selector=sel)
        sched.update_endpoints(self._endpoints(
            measured=dict(request_total_slots=8, kv_total_blocks=100),
            never_seen=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        sched.schedule(160, MatchResult())
        c = sel.last_components["never_seen"]
        assert c["cold"] is True
        assert 0.0 < c["transfer_s"] < float("inf")
        # the cold prior equals the fleet median, so equal-load equal-
        # overlap candidates tie instead of the cold one being shut out
        assert c["transfer_s"] == pytest.approx(
            sel.last_components["measured"]["transfer_s"])
        assert c["transfer_norm"] <= sel.max_penalty
        assert ROUTER_STATS.cold_scored >= 1

    def test_degraded_freeze_pins_cost_term(self):
        # stale-snapshot degraded mode: the cost term freezes at its
        # last live values — new (possibly stale-amplified) signals
        # don't move the ranking until the freeze lifts
        model = self._model(a=1 << 30, b=1 << 30)
        sel = self._selector(model)
        sched = KvScheduler(block_size=16, selector=sel)
        sched.update_endpoints(self._endpoints(
            a=dict(request_total_slots=8, kv_total_blocks=100),
            b=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        sched.schedule(160, MatchResult())
        live_a = sel.last_components["a"]["transfer_s"]
        sel.freeze_cost(True)
        # the link "collapses" while degraded — frozen scoring must NOT see it
        for _ in range(8):
            model.observe("a", 1 << 10, 1.0)
        sched.schedule(160, MatchResult())
        frozen = sel.last_components["a"]
        assert frozen["frozen"] is True
        assert frozen["transfer_s"] == pytest.approx(live_a)
        sel.freeze_cost(False)
        sched.schedule(160, MatchResult())
        thawed = sel.last_components["a"]
        assert thawed["frozen"] is False
        assert thawed["transfer_s"] > live_a   # the collapse is visible again

    def test_router_stats_and_components_exposed(self):
        from dynamo_tpu.kv_router.stats import ROUTER_STATS
        ROUTER_STATS.reset()
        model = self._model(w1=1 << 28)
        sel = self._selector(model)
        sched = KvScheduler(block_size=16, selector=sel)
        sched.update_endpoints(self._endpoints(
            w1=dict(request_total_slots=8, kv_total_blocks=100)))
        from dynamo_tpu.kv_router.indexer import MatchResult
        sched.schedule(64, MatchResult())
        assert ROUTER_STATS.transfer_scored == 1
        assert sel.last_pick["worker_id"] == "w1"
        for key in ("overlap", "kv_usage", "active", "transfer_s",
                    "transfer_norm", "cold", "frozen", "logit"):
            assert key in sel.last_pick


class TestOrphanEvents:
    def test_unknown_parent_store_is_dropped(self):
        """A mid-sequence page whose parent is unknown (router restarted)
        must NOT root-attach — that would forge a fake depth-1 prefix."""
        tree = RadixTree()
        tree.apply_event(stored("w1", [(102, 2)], parent=101))  # orphan
        assert tree.find_matches([2]).scores == {}
        assert tree.num_nodes() == 0

    def test_fresh_worker_defaults_are_bumpable(self):
        """Never-scraped instances get unit totals so the optimistic bump
        spreads traffic instead of flooding one cold worker."""
        sched = KvScheduler(block_size=16,
                            selector=DefaultWorkerSelector(rng=random.Random(0)))
        sched.update_endpoints(ProcessedEndpoints({
            "cold": WorkerMetrics(request_total_slots=1, kv_total_blocks=1),
            "warm": WorkerMetrics(request_active_slots=1,
                                  request_total_slots=8,
                                  kv_active_blocks=10, kv_total_blocks=100)}))
        from dynamo_tpu.kv_router.indexer import MatchResult
        first = sched.schedule(64, MatchResult())
        second = sched.schedule(64, MatchResult())
        assert first == "cold"
        assert second == "warm"  # bump made the cold worker look busy


class TestIndexerTombstones:
    def test_late_event_cannot_resurrect_removed_worker(self):
        idx = KvIndexer(block_size=4)
        idx.apply_event(stored("w1", [(101, 1)]))
        idx.remove_worker("w1")
        idx.apply_event(stored("w1", [(102, 2)]))  # in-flight straggler
        assert idx.find_matches([1]).scores == {}
        assert idx.find_matches([2]).scores == {}
        # revival (worker id re-appeared live) accepts events again
        idx.revive_worker("w1")
        idx.apply_event(stored("w1", [(103, 3)]))
        assert idx.find_matches([3]).scores == {"w1": 1}

    def test_sharded_merges_frequencies(self):
        sharded = KvIndexerSharded(block_size=4, num_shards=2,
                                   expiration_duration_s=60.0)
        sharded.apply_event(stored("w1", [(101, 1)]))
        sharded.apply_event(stored("w2", [(201, 1)]))
        res = sharded.find_matches([1])
        assert res.scores == {"w1": 1, "w2": 1}
        assert res.frequencies and res.frequencies[0] >= 1


class TestAllocatorEventBridge:
    def test_allocator_events_to_index(self):
        """Engine allocator seal/evict events round-trip into a queryable
        index: the tokens a worker cached are found by a token query."""
        alloc = PageAllocator(num_pages=8, page_size=4)
        toks = list(range(8))
        p0, p1 = alloc.allocate(), alloc.allocate()
        h0 = alloc.seal(p0, 0, toks[:4])
        h1 = alloc.seal(p1, h0, toks[4:])
        events = alloc.drain_events()
        assert [e[0] for e in events] == ["stored", "stored"]
        assert events[0][2] == h0 == page_hash(0, toks[:4])
        assert events[0][4] == tokens_hash(toks[:4])

        idx = KvIndexer(block_size=4)
        for kind, _pid, sh, parent, th in events:
            idx.apply_event(stored("w1", [(sh, th)], parent=parent or None))
        assert idx.find_matches_for_tokens(toks).scores == {"w1": 2}


class TestRouterEndToEnd:
    def test_router_over_memory_plane(self):
        async def main():
            plane = MemoryPlane()
            worker_rts = []
            pubs = {}
            for wid in ("w1", "w2"):
                rt = await DistributedRuntime.create_local(plane, wid)
                comp = rt.namespace("ns").component("worker")
                mpub = KvMetricsPublisher()
                mpub.update(WorkerMetrics(
                    request_active_slots=0, request_total_slots=8,
                    kv_active_blocks=0, kv_total_blocks=100))

                async def engine(request, context, wid=wid):
                    yield {"worker": wid}

                await comp.endpoint("generate").serve(
                    engine, stats_handler=mpub.stats_handler)
                pubs[wid] = (comp, mpub)
                worker_rts.append(rt)

            rrt = await DistributedRuntime.create_local(plane, "router")
            comp = rrt.namespace("ns").component("worker")
            client = comp.endpoint("generate").client()
            await client.start()
            await client.wait_for_instances()
            router = await KvRouter(comp, client, block_size=4,
                                    scrape_interval_s=0.05).start()
            await asyncio.sleep(0.15)  # let a scrape land
            assert set(router.scheduler.endpoints.workers) == {"w1", "w2"}

            # w2 publishes that it cached the prompt's first two pages
            toks = list(range(100, 116))
            alloc = PageAllocator(8, 4)
            pids = [alloc.allocate(), alloc.allocate()]
            parent = 0
            for i, pid in enumerate(pids):
                parent = alloc.seal(pid, parent, toks[i * 4:(i + 1) * 4])
            await KvEventPublisher(pubs["w2"][0], "w2").publish_allocator_events(
                alloc.drain_events())
            await asyncio.sleep(0.1)  # event pump

            assert router.find_matches_for_tokens(toks).scores == {"w2": 2}
            assert await router.schedule(toks) == "w2"

            # dead worker is purged from index + endpoints on next scrape
            await worker_rts[1].shutdown()
            await asyncio.sleep(0.3)
            assert router.find_matches_for_tokens(toks).scores == {}
            assert set(router.scheduler.endpoints.workers) == {"w1"}
            assert await router.schedule(toks) == "w1"

            await router.stop()
            await rrt.shutdown()
            await worker_rts[0].shutdown()

        asyncio.run(main())


class TestInstanceLifecycleEviction:
    def test_dereg_evicts_index_immediately_and_drain_fences(self):
        """Satellite fix: a dead worker's radix-index entries go at
        WATCH-EVENT time (deregistration/lease-expiry), not at the next
        metrics scrape — before this, its cached-prefix score kept
        attracting routes until the circuit breaker tripped, one failed
        dispatch per stream. DRAINING does the same fence while the
        instance stays alive for its in-flight streams."""
        async def main():
            plane = MemoryPlane()
            worker_rts, serveds, pubs = [], {}, {}
            for wid in ("w1", "w2"):
                rt = await DistributedRuntime.create_local(plane, wid)
                comp = rt.namespace("ns").component("worker")
                mpub = KvMetricsPublisher()
                mpub.update(WorkerMetrics(
                    request_active_slots=0, request_total_slots=8,
                    kv_active_blocks=0, kv_total_blocks=100))

                async def engine(request, context, wid=wid):
                    yield {"worker": wid}

                serveds[wid] = await comp.endpoint("generate").serve(
                    engine, stats_handler=mpub.stats_handler)
                pubs[wid] = comp
                worker_rts.append(rt)

            rrt = await DistributedRuntime.create_local(plane, "router")
            comp = rrt.namespace("ns").component("worker")
            client = comp.endpoint("generate").client()
            await client.start()
            await client.wait_for_instances()
            # scrape interval >> test length: the initial scrape seeds the
            # scheduler, then ONLY the watch listener can evict — which is
            # exactly what this test pins down
            router = await KvRouter(comp, client, block_size=4,
                                    scrape_interval_s=60.0).start()
            await router.aggregator.scrape_once()   # seed deterministically
            assert set(router.scheduler.endpoints.workers) == {"w1", "w2"}

            toks = list(range(100, 116))
            alloc = PageAllocator(8, 4)
            pids = [alloc.allocate(), alloc.allocate()]
            parent = 0
            for i, pid in enumerate(pids):
                parent = alloc.seal(pid, parent, toks[i * 4:(i + 1) * 4])
            await KvEventPublisher(pubs["w2"], "w2").publish_allocator_events(
                alloc.drain_events())
            await asyncio.sleep(0.1)  # event pump
            assert router.find_matches_for_tokens(toks).scores == {"w2": 2}
            assert await router.schedule(toks) == "w2"

            # DRAIN: the fence lands on the watch put, with no scrape —
            # prefix scores gone, schedule avoids w2, instance still alive
            await serveds["w2"].mark_draining()
            await asyncio.sleep(0.1)
            assert router.find_matches_for_tokens(toks).scores == {}
            assert client.draining_ids() == ["w2"]
            assert await router.schedule(toks) == "w1"
            assert "w2" in client.instances   # alive for in-flight streams

            # DEREGISTRATION (lease gone): purged from index AND scheduler
            # at watch-delete time, again without any scrape
            await worker_rts[1].shutdown()
            await asyncio.sleep(0.2)
            assert router.find_matches_for_tokens(toks).scores == {}
            assert set(router.scheduler.endpoints.workers) == {"w1"}
            assert await router.schedule(toks) == "w1"

            await router.stop()
            await rrt.shutdown()
            await worker_rts[0].shutdown()

        asyncio.run(asyncio.wait_for(main(), 60))


class TestIncrementalEviction:
    def _bulk_store(self, tree, worker, n_nodes, fanout=64):
        """Store pages as `fanout` independent chains; returns the node
        count actually stored (n_nodes rounded down to the fanout)."""
        per_chain = n_nodes // fanout
        eid = 0
        for c in range(fanout):
            parent = None
            for i in range(per_chain):
                bh = (worker, c, i).__hash__() & 0x7FFFFFFFFFFFFFFF
                th = (c << 20) | i
                tree.apply_event(stored(worker, [(bh, th)], parent=parent,
                                        eid=eid))
                parent = bh
                eid += 1
        return per_chain * fanout

    def test_eviction_cost_is_bounded_per_call(self):
        """Satellite: evicting a 100k-node worker must not stall
        find_matches — remove_worker does one bounded chunk, the rest
        drains EVICT_AMORTIZE nodes per query/event, and the dead
        worker stops scoring IMMEDIATELY."""
        from dynamo_tpu.kv_router.indexer import (
            EVICT_AMORTIZE, EVICT_CHUNK, RadixTree,
        )
        tree = RadixTree()
        n = self._bulk_store(tree, "big", 20_000)
        tree.apply_event(stored("small", [(1, (0 << 20) | 0)]))
        assert tree.num_nodes() == n  # small shares the first page node
        tree.remove_worker("big")
        backlog0 = tree.eviction_backlog()
        assert backlog0 == n - EVICT_CHUNK   # exactly one chunk done
        # the dead worker never scores again, even with backlog pending
        res = tree.find_matches([(0 << 20) | 0])
        assert "big" not in res.scores and res.scores == {"small": 1}
        # ...and that query drained exactly one amortized chunk
        assert backlog0 - tree.eviction_backlog() == EVICT_AMORTIZE
        # explicit draining finishes the purge; shared node survives
        while tree.eviction_backlog():
            tree.process_evictions()
        assert tree.find_matches([(0 << 20) | 0]).scores == {"small": 1}
        assert tree.num_nodes() == 1
        assert tree.worker_block_count("big") == 0

    def test_eviction_microbench_amortized_call_is_cheap(self):
        """Microbench shape: with a 20k-node eviction pending, a single
        find_matches costs a bounded chunk — orders of magnitude below
        the full purge (time-asserted loosely; the hard bound is the
        chunk-size assert above)."""
        import time as _t
        from dynamo_tpu.kv_router.indexer import RadixTree
        tree = RadixTree()
        self._bulk_store(tree, "big", 20_000)
        tree.remove_worker("big")
        t0 = _t.perf_counter()
        tree.find_matches([123])
        single = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        while tree.eviction_backlog():
            tree.process_evictions()
        full_rest = _t.perf_counter() - t0
        # one amortized call does ~64 of ~19k remaining nodes; give the
        # comparison a wide margin to stay timing-robust in CI
        assert single < full_rest, (single, full_rest)

    def test_sharded_parity_under_interleaved_churn(self):
        """Satellite: KvIndexerSharded stays parity-exact with KvIndexer
        under interleaved apply_event / remove_worker / revive_worker
        churn — including while evictions are mid-backlog."""
        idx = KvIndexer(block_size=2, native=False)
        sharded = KvIndexerSharded(block_size=2, num_shards=3)
        rng = random.Random(7)
        workers = [f"w{i}" for i in range(6)]
        removed = set()
        for eid in range(600):
            op = rng.random()
            w = rng.choice(workers)
            if op < 0.70:
                chain = [(rng.randrange(1 << 30), rng.randrange(16))
                         for _ in range(rng.randrange(1, 4))]
                ev = stored(w, chain, eid=eid)
                idx.apply_event(ev)
                sharded.apply_event(ev)
            elif op < 0.85:
                idx.remove_worker(w)
                sharded.remove_worker(w)
                removed.add(w)
            else:
                idx.revive_worker(w)
                sharded.revive_worker(w)
                removed.discard(w)
            if eid % 20 == 0:
                for _ in range(10):
                    q = [rng.randrange(16) for _ in range(3)]
                    assert idx.find_matches(q).scores == \
                        sharded.find_matches(q).scores, (eid, q)
        # drain all pending evictions: parity must hold at the end too
        idx.process_evictions(1 << 30)
        sharded.process_evictions(1 << 30)
        for _ in range(50):
            q = [rng.randrange(16) for _ in range(4)]
            assert idx.find_matches(q).scores == sharded.find_matches(q).scores


class TestDegradedMode:
    def test_lag_storm_round_trips_degraded_mode(self):
        """Event-plane lag drives the router into the stale-snapshot
        degraded mode (scheduling keeps answering on last-good state)
        and back out once caught up, with the flag visible on CP_STATS."""
        from dynamo_tpu.runtime.cpstats import CP_STATS

        async def main():
            plane = MemoryPlane()
            wrt = await DistributedRuntime.create_local(plane, "w1")
            comp = wrt.namespace("ns").component("worker")
            mpub = KvMetricsPublisher()
            mpub.update(WorkerMetrics(request_total_slots=8,
                                      kv_total_blocks=100))

            async def engine(request, context):
                yield {}

            await comp.endpoint("generate").serve(
                engine, stats_handler=mpub.stats_handler)
            rrt = await DistributedRuntime.create_local(plane, "router")
            rcomp = rrt.namespace("ns").component("worker")
            client = rcomp.endpoint("generate").client()
            await client.start()
            router = await KvRouter(rcomp, client, block_size=4,
                                    scrape_interval_s=0.05,
                                    degraded_lag_s=0.2,
                                    degraded_min_s=0.2).start()
            await router.aggregator.scrape_once()
            pub = KvEventPublisher(comp, "w1")

            # stale-ts events = the lag storm (publisher clock is the
            # event ts; a 1s-old ts on arrival == 1s event-plane lag)
            import time as _t
            from dynamo_tpu.kv_router.protocols import (
                KvCacheEvent, KvCacheStoreData, KvCacheStoredBlockData,
                RouterEvent,
            )
            for i in range(3):
                ev = RouterEvent("w1", KvCacheEvent(i, KvCacheStoreData(
                    parent_hash=None,
                    blocks=[KvCacheStoredBlockData(100 + i, i)])),
                    ts=_t.time() - 1.0)
                await comp.publish("kv_events", ev.pack())
            deadline = asyncio.get_running_loop().time() + 5
            while not router.degraded:
                assert asyncio.get_running_loop().time() < deadline, \
                    "router never entered degraded mode"
                await asyncio.sleep(0.02)
            assert CP_STATS.router_degraded == 1
            # scheduling still answers, on last-good state
            assert await router.schedule(list(range(8))) == "w1"

            # fresh events + idle ticks: lag decays, mode exits
            await pub.publish_stored(None, [(200, 7)])
            deadline = asyncio.get_running_loop().time() + 5
            while router.degraded:
                assert asyncio.get_running_loop().time() < deadline, \
                    "router never exited degraded mode"
                await asyncio.sleep(0.05)
            assert CP_STATS.router_degraded == 0
            assert router.degraded_entries >= 1
            await router.stop()
            await rrt.shutdown()
            await wrt.shutdown()

        asyncio.run(asyncio.wait_for(main(), 60))


class TestAggregatorStatlessWorkers:
    def test_live_statless_instance_never_counts_removed(self):
        """A live instance whose $STATS scrape fails (e.g. an engine with no
        stats handler) must NOT appear in the aggregator's `removed` set —
        removal purges the worker's radix-index entries, which made KV
        routing effectively random (regression: scrape_once computed
        `removed` before the live-instance fallback)."""
        class FakeClient:
            instances = {"wa": {}, "wb": {}}
            async def scrape_stats(self, timeout=2.0):
                return {}  # nobody answers $STATS

        async def main():
            agg = KvMetricsAggregator(FakeClient(), interval_s=999)
            removed_log = []
            agg.on_update(lambda eps, removed: removed_log.append(set(removed)))
            for _ in range(3):
                eps = await agg.scrape_once()
            assert set(eps.workers) == {"wa", "wb"}
            assert all(r == set() for r in removed_log), removed_log
            # fallback metrics keep the optimistic bump meaningful
            assert eps.workers["wa"].request_total_slots == 1

        asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
            main())
