"""Multi-tenant QoS (ISSUE 14 / ROADMAP item 5): priority classes,
weighted-fair scheduling, in-flight preemption.

Layers under test:
- runtime/qos.py units: StridePicker weighted ratios + bounded-aging
  no-starvation, TokenBucket, AdmissionState (weighted-fair admission,
  batch-first displacement, class-scaled Retry-After), select_victim.
- frontend/reliability.AdmissionControl: class-aware async wrapper
  (weighted-fair grants, displacement sheds, legacy path unchanged).
- engine/scheduler.py: class-ordered waiting queue with the aging
  bound, policy-driven victim selection, cross-class preemption
  charged against (and bounded by) the preemptor's class budget.
- engine preempt-resume EXACTNESS: a decode preempted at an arbitrary
  step and resumed is token-identical (greedy + seeded-sampled) on the
  aggregated AND the disagg (remote-prefilled) paths, with the epoch
  bump pinning that the stale device carry can never be decoded from.
- disagg/queue.PrefillQueue: class sub-queues, weighted-deficit
  dequeue, lease/ack routing, depth.
- per-class serving histograms -> rollup qos/* series -> qos_slo_specs.
- the committed QOS_r14.json storm replays bit-identically.
"""
import asyncio
import json
import os
import sys

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import (
    EngineRequest, SamplingParams, Scheduler,
)
from dynamo_tpu.runtime.qos import (
    QOS_STATS, AdmissionState, QosClass, QosPolicy, StridePicker,
    TokenBucket, qos_label, qos_of, select_victim,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine(num_pages=64, **kw):
    # the test_disagg geometry (same compiled program shapes; num_pages
    # only sizes the allocator) so the jit cache carries across files
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=4,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=512, **kw), seed=0)


@pytest.fixture(autouse=True)
def clean_qos_stats():
    QOS_STATS.reset()
    yield
    QOS_STATS.reset()


# -- weighted-fair picker ------------------------------------------------------

def test_stride_picker_service_ratios_match_weights():
    pk = StridePicker(QosPolicy())
    classes = ["interactive", "standard", "batch"]
    for _ in range(120):
        pk.charge(pk.order(classes)[0], classes)
    # 8 : 3 : 1 exactly at 120 rounds
    assert pk.served == {"interactive": 80, "standard": 30, "batch": 10}
    assert pk.aging_promotions == 0   # stride alone bounds the skew here


def test_stride_picker_bounded_aging_promotes_starved_class():
    policy = QosPolicy((
        QosClass("hi", priority=1, weight=1000.0),
        QosClass("lo", priority=0, weight=1.0),
    ), default="hi", aging_limit=5)
    pk = StridePicker(policy)
    served_lo_at = []
    for i in range(40):
        cls = pk.order(["hi", "lo"])[0]
        pk.charge(cls, ["hi", "lo"])
        if cls == "lo":
            served_lo_at.append(i)
    # without aging, weight 1000:1 would starve `lo` for ~1000 rounds;
    # the bound forces service within aging_limit+1 rounds of backlog
    assert served_lo_at and served_lo_at[0] <= 5
    assert pk.aging_promotions >= 1
    # and consecutive lo services stay <= aging_limit+1 apart
    gaps = [b - a for a, b in zip(served_lo_at, served_lo_at[1:])]
    assert all(g <= 6 for g in gaps)


def test_token_bucket_rate_and_burst():
    tb = TokenBucket(rate_per_s=2.0, burst=4.0)
    assert all(tb.take(0.0) for _ in range(4))   # burst
    assert not tb.take(0.0)                      # empty
    assert tb.take(1.0)                          # 2 tokens refilled
    assert tb.take(1.0)
    assert not tb.take(1.0)
    assert TokenBucket(0.0, 0.0).take(123.0)     # 0 = unlimited


# -- admission state -----------------------------------------------------------

def _policy(aging=16):
    return QosPolicy(aging_limit=aging)


def test_admission_weighted_fair_and_batch_first_displacement():
    st = AdmissionState(_policy(), max_inflight=2, max_queued=2)
    assert st.try_admit("interactive", 0.0).kind == "admit"
    assert st.try_admit("batch", 0.0).kind == "admit"
    assert st.try_admit("batch", 0.0).kind == "queue"
    assert st.try_admit("batch", 0.0).kind == "queue"
    # queue full + higher-priority arrival: the BATCH waiter sheds
    d = st.try_admit("interactive", 0.0)
    assert d.kind == "displace" and d.victim_class == "batch"
    # queue now holds 1 batch + 1 interactive; a batch arrival cannot
    # displace anything (nothing below it) -> sheds itself
    assert st.try_admit("batch", 0.0).kind == "shed"
    # freed slot grants weighted-fair: interactive (weight 8) first
    st.note_released("interactive")
    g = st.grant()
    assert g == "interactive"
    st.note_granted(g)


def test_admission_retry_after_scales_with_class_queue_depth():
    st = AdmissionState(_policy(), max_inflight=1, max_queued=8,
                        retry_after_s=2)
    assert st.try_admit("batch", 0.0).kind == "admit"
    for _ in range(3):
        assert st.try_admit("batch", 0.0).kind == "queue"
    # batch hint scales with BATCH depth; interactive's does not
    assert st.retry_after("batch") == 2 * (1 + 3)
    assert st.retry_after("interactive") == 2


def test_admission_rate_budget_sheds_over_bucket():
    policy = QosPolicy((
        QosClass("interactive", priority=2, weight=8.0),
        QosClass("standard", priority=1, weight=3.0),
        QosClass("batch", priority=0, weight=1.0,
                 rate_per_s=1.0, burst=2.0),
    ))
    st = AdmissionState(policy, max_inflight=100, max_queued=10)
    kinds = [st.try_admit("batch", 0.0).kind for _ in range(4)]
    assert kinds == ["admit", "admit", "shed", "shed"]   # burst of 2
    assert st.try_admit("batch", 1.0).kind == "admit"    # refilled
    assert st.try_admit("interactive", 0.0).kind == "admit"  # unlimited


def test_admission_control_async_weighted_fair_and_displacement():
    from dynamo_tpu.frontend.reliability import (
        AdmissionControl, AdmissionShed,
    )

    async def main():
        adm = AdmissionControl(max_inflight=1, max_queued=2,
                               queue_timeout_s=5.0, policy=_policy())
        await adm.acquire(qos="standard")          # holds the slot
        b = asyncio.create_task(adm.acquire(qos="batch"))      # queued
        i = asyncio.create_task(adm.acquire(qos="interactive"))
        await asyncio.sleep(0.01)
        # queue full; a second interactive displaces the batch waiter
        i2 = asyncio.create_task(adm.acquire(qos="interactive"))
        with pytest.raises(AdmissionShed) as exc:
            await b
        assert exc.value.qos == "batch"
        # freed slot grants interactive (weighted-fair)
        adm.release(qos="standard")
        await asyncio.wait_for(i, 1.0)
        adm.release(qos="interactive")
        await asyncio.wait_for(i2, 1.0)
        adm.release(qos="interactive")

    asyncio.run(main())


def test_admission_control_legacy_path_unchanged():
    from dynamo_tpu.frontend.reliability import (
        AdmissionControl, AdmissionShed,
    )

    async def main():
        adm = AdmissionControl(max_inflight=1, max_queued=0,
                               retry_after_s=3)
        await adm.acquire()
        with pytest.raises(AdmissionShed) as exc:
            await adm.acquire()
        assert exc.value.retry_after_s == 3 and exc.value.qos == ""
        adm.release()
        await adm.acquire()     # slot free again

    asyncio.run(main())


# -- victim selection + scheduler policy ---------------------------------------

class _Seq:
    def __init__(self, qos, computed):
        self.qos = qos
        self.num_computed = computed


def test_select_victim_lowest_class_then_youngest():
    running = [_Seq("interactive", 2), _Seq("batch", 50),
               _Seq("batch", 10), None, _Seq("standard", 1)]
    v = select_victim(running)
    assert v.qos == "batch" and v.num_computed == 10   # youngest batch
    # same-class pressure: all one class keeps youngest-first
    same = [_Seq("standard", 9), _Seq("standard", 3), _Seq("standard", 7)]
    assert select_victim(same).num_computed == 3
    # below_prio restricts to strictly lower classes
    assert select_victim([_Seq("interactive", 1)],
                         below_prio=2) is None


def _sched(num_pages=64):
    return Scheduler(EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=2,
        max_prefill_chunk=16, prefill_buckets=(8, 16),
        max_model_len=128, decode_steps=4))


def test_waiting_queue_class_bypass_with_aging_pin():
    s = _sched()
    s.qos_policy = QosPolicy(aging_limit=2)
    for i in range(3):
        s.add_request(EngineRequest(
            f"b{i}", list(range(3, 12)), SamplingParams(max_tokens=2),
            qos="batch"))
    # interactive arrivals bypass the batch band (FIFO within class)...
    s.add_request(EngineRequest("i0", list(range(3, 12)),
                                SamplingParams(max_tokens=2),
                                qos="interactive"))
    s.add_request(EngineRequest("i1", list(range(3, 12)),
                                SamplingParams(max_tokens=2),
                                qos="interactive"))
    assert [x.request_id for x in s.waiting] == \
        ["i0", "i1", "b0", "b1", "b2"]
    # ...but every batch seq has now been bypassed aging_limit times:
    # they PIN, and further interactive arrivals queue BEHIND them —
    # each batch request is jumped at most aging_limit times, bounded
    s.add_request(EngineRequest("i2", list(range(3, 12)),
                                SamplingParams(max_tokens=2),
                                qos="interactive"))
    assert [x.request_id for x in s.waiting] == \
        ["i0", "i1", "b0", "b1", "b2", "i2"]
    assert all(x.qos_bypassed <= 2 for x in s.waiting)
    assert QOS_STATS.sched_aging_pins >= 1


def test_cross_class_preempt_charged_and_budget_bounded():
    s = _sched(num_pages=4)   # 32 token slots: genuine page pressure
    policy = QosPolicy((
        QosClass("interactive", priority=2, weight=8.0, preempt_budget=1),
        QosClass("standard", priority=1, weight=3.0),
        QosClass("batch", priority=0, weight=1.0),
    ), default="standard")
    s.qos_policy = policy
    # two batch requests take both slots and all pages
    for i in range(2):
        s.add_request(EngineRequest(
            f"b{i}", list(range(3, 12)),   # 9 tokens + 5 = 2 pages each
            SamplingParams(max_tokens=5, ignore_eos=True), qos="batch"))
    while s.waiting:
        plan = s.schedule()
        for r in range(len(plan.seqs)):
            if plan.seqs[r] is not None:
                s.commit_prefill_row(plan, r, 7)
    assert sum(1 for x in s.running if x is not None) == 2
    # interactive arrival: no free page -> cross-class preemption,
    # charged against interactive's budget
    s.add_request(EngineRequest("hi", list(range(3, 12)),
                                SamplingParams(max_tokens=5,
                                               ignore_eos=True),
                                qos="interactive"))
    plan = s.schedule()
    assert plan is not None
    assert s._qos_preempt_debt == {"interactive": 1}
    assert QOS_STATS.preemptions_total == 1
    assert QOS_STATS.preempt_by_class == {"interactive": 1}
    assert QOS_STATS.preempted_by_class == {"batch": 1}
    # budget (1) exhausted: a second interactive cannot preempt the
    # remaining batch decode
    s.add_request(EngineRequest("hi2", list(range(20, 29)),
                                SamplingParams(max_tokens=5,
                                               ignore_eos=True),
                                qos="interactive"))
    before = QOS_STATS.preemptions_total
    s._preempt_for(next(x for x in s.waiting
                        if x.request_id == "hi2"))
    assert QOS_STATS.preemptions_total == before
    assert QOS_STATS.preempt_denied_budget >= 1
    # the victim re-queued at the head of its class band
    victims = [x.request_id for x in s.waiting if x.qos == "batch"]
    assert victims and victims[0].startswith("b")


# -- preempt-resume exactness (aggregated) -------------------------------------

def _run_to_completion(eng, want):
    toks = {rid: [] for rid in want}
    while eng.has_work():
        for ev in eng.step():
            if ev.request_id in toks and ev.token is not None:
                toks[ev.request_id].append(ev.token)
    return toks


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_preempt_resume_token_identical_aggregated(temperature):
    """A batch decode preempted at an arbitrary step by an interactive
    arrival resumes TOKEN-IDENTICALLY (greedy + seeded-sampled), the
    epoch bump guaranteeing the stale device carry is never decoded
    from; the preemption is charged to the interactive class budget."""
    prompt_b = list(range(3, 33))            # 30 tokens
    prompt_i = list(range(40, 60))           # 20 tokens
    params_b = SamplingParams(max_tokens=10, temperature=temperature,
                              seed=7, ignore_eos=True)
    params_i = SamplingParams(max_tokens=6, temperature=temperature,
                              seed=11, ignore_eos=True)
    # oracles: each request alone on an identical engine
    expect_b = make_engine().generate(prompt_b, params_b, "b")
    expect_i = make_engine().generate(prompt_i, params_i, "i")

    # 5 pages of 8: the batch request's decode-window reservation
    # (prompt 30 + max 10 -> 5 pages) takes the whole allocator
    eng = make_engine(num_pages=5)
    eng.add_request(EngineRequest("b", prompt_b, params_b, qos="batch"))
    emitted = []
    while len(emitted) < 3:                  # arbitrary mid-decode step
        for ev in eng.step():
            if ev.token is not None:
                emitted.append(ev.token)
    seq_b = next(x for x in eng.scheduler.running if x is not None)
    epoch_before = seq_b.epoch
    eng.add_request(EngineRequest("i", prompt_i, params_i,
                                  qos="interactive"))
    toks = _run_to_completion(eng, ("b", "i"))
    # the interactive arrival actually preempted the batch decode...
    assert QOS_STATS.preemptions_total >= 1
    assert QOS_STATS.preempt_by_class.get("interactive", 0) >= 1
    # ...bumping the victim's epoch so the engine's device-resident
    # decode-carry signature (request_id, epoch) can never match the
    # stale pre-preemption carry
    assert seq_b.epoch > epoch_before
    # both streams token-identical to their uninterrupted oracles
    assert emitted + toks["b"] == expect_b
    assert toks["i"] == expect_i
    eng.close()


def make_engine1(**kw):
    """One-slot variant: an interactive arrival can only run by
    preempting the single running decode (slot pressure, not pages)."""
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=1,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=512, **kw), seed=0)


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_preempt_resume_token_identical_disagg(temperature):
    """Same exactness on the DISAGG path: a remotely-prefilled decode
    (up-front allocation + KV inject + activate) preempted mid-decode
    resumes token-identically — the committed-prefix recompute path of
    the decode engine is the resume mechanism."""
    import jax
    prompt = list(range(40, 60))
    params = SamplingParams(max_tokens=24, temperature=temperature,
                            seed=5, ignore_eos=True)
    params_i = SamplingParams(max_tokens=4, temperature=temperature,
                              seed=9, ignore_eos=True)
    expect = make_engine1().generate(prompt, params, "direct")
    expect_i = make_engine1().generate(list(range(10, 30)), params_i,
                                       "i")

    prefill_eng = make_engine()
    decode_eng = make_engine1()   # single decode slot
    alloc = decode_eng.allocate_remote(EngineRequest("r", prompt, params,
                                                     qos="batch"))
    assert alloc is not None
    prefill_eng.add_request(EngineRequest("r", prompt, params,
                                          prefill_only=True))
    outs = []
    while prefill_eng.has_work():
        outs.extend(prefill_eng.step())
    first = outs[0].token
    seq = prefill_eng.scheduler.parked["r"]
    pages = prefill_eng.extract_pages(seq.pages)
    k = jax.device_put(pages["k"], decode_eng.cache_sharding)
    v = jax.device_put(pages["v"], decode_eng.cache_sharding)
    decode_eng.inject_pages(alloc.page_ids, k, v)
    prefill_eng.release_parked("r")
    decode_eng.activate_remote("r", first)
    toks = [first]
    while len(toks) < 3:                  # mid-decode on the disagg seq
        for ev in decode_eng.step():
            if ev.token is not None:
                toks.append(ev.token)
    # interactive arrival on the decode engine: pages exhausted by the
    # remote seq's reservation -> policy preemption -> resume
    decode_eng.add_request(EngineRequest("i", list(range(10, 30)),
                                         params_i, qos="interactive"))
    done = _run_to_completion(decode_eng, ("r", "i"))
    assert QOS_STATS.preemptions_total >= 1
    assert toks + done["r"] == expect
    assert done["i"] == expect_i
    prefill_eng.close()
    decode_eng.close()


# -- class-aware prefill queue -------------------------------------------------

def test_prefill_queue_class_subqueues_weighted_dequeue_and_ack():
    from dynamo_tpu.disagg import PrefillQueue, RemotePrefillRequest
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    async def main():
        plane = MemoryPlane()
        policy = QosPolicy(aging_limit=4)
        q = PrefillQueue(plane.messaging, "ns", "m", qos_policy=policy)

        def item(rid, qos):
            return RemotePrefillRequest(
                engine_id="e", request_id=rid, token_ids=[1, 2, 3],
                page_ids=[0], page_size=8, qos=qos)

        # enqueue a batch burst ahead of one interactive
        for i in range(4):
            await q.enqueue(item(f"b{i}", "batch"))
        await q.enqueue(item("i0", "interactive"))
        assert await q.depth() == 5
        # weighted-deficit dequeue serves the interactive item FIRST
        # despite 4 batch items enqueued earlier
        got, tok = await q.dequeue_leased(timeout=1.0, lease_s=5.0)
        assert got.request_id == "i0" and got.qos == "interactive"
        await q.ack(tok)
        # the batch backlog still drains completely (no starvation)
        seen = []
        for _ in range(4):
            got, tok = await q.dequeue_leased(timeout=1.0, lease_s=5.0)
            seen.append(got.request_id)
            await q.ack(tok)
        assert sorted(seen) == ["b0", "b1", "b2", "b3"]
        assert await q.depth() == 0
        # empty queue + timeout -> None (bounded poll)
        assert await q.dequeue_leased(timeout=0.12) is None

    asyncio.run(main())


def test_prefill_queue_without_policy_is_fifo():
    from dynamo_tpu.disagg import PrefillQueue, RemotePrefillRequest
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    async def main():
        plane = MemoryPlane()
        q = PrefillQueue(plane.messaging, "ns", "m")
        for i in range(3):
            await q.enqueue(RemotePrefillRequest(
                engine_id="e", request_id=f"r{i}", token_ids=[1],
                page_ids=[0], page_size=8,
                qos="interactive" if i == 2 else "batch"))
        order = []
        for _ in range(3):
            got, tok = await q.dequeue_leased(timeout=1.0)
            order.append(got.request_id)
            await q.ack(tok)
        assert order == ["r0", "r1", "r2"]   # strict FIFO, class ignored

    asyncio.run(main())


# -- baggage + labels ----------------------------------------------------------

def test_qos_baggage_helpers_and_router_weighting():
    from dynamo_tpu.kv_router.indexer import MatchResult
    from dynamo_tpu.kv_router.scheduler import (
        SchedulingRequest, TransferAwareSelector,
    )
    from dynamo_tpu.kv_router.scoring import (
        ProcessedEndpoints, WorkerMetrics,
    )
    from dynamo_tpu.observability.fleet import TransferCostModel

    assert qos_of({"qos": "batch"}) == "batch"
    assert qos_of(None) == "" and qos_of({}) == ""
    assert qos_label({"qos": "interactive"}) == "interactive"
    assert qos_label({}) == "standard"       # default partition
    assert qos_label({"qos": "bogus"}) == "standard"

    # class latency weight scales the transfer cost term: the slow
    # link holds a big resident prefix (overlap win 1.6) but costs
    # ~2 cost-horizons of transfer — decisive only through the class
    # weight: batch (x0.5 -> penalty 1.0) keeps the prefix win,
    # interactive (x2.0 -> penalty 4.0) routes to the fast link
    model = TransferCostModel()
    model.observe("slow", 2_000_000, 1.0)    # 2 MB/s
    model.observe("fast", 100_000_000, 0.1)  # 1 GB/s
    eps = ProcessedEndpoints(workers={
        "slow": WorkerMetrics(kv_active_blocks=0, kv_total_blocks=100,
                              request_active_slots=0,
                              request_total_slots=8),
        "fast": WorkerMetrics(kv_active_blocks=0, kv_total_blocks=100,
                              request_active_slots=0,
                              request_total_slots=8),
    })
    sel = TransferAwareSelector(rng=__import__("random").Random(0),
                                cost_model=model)
    overlap = MatchResult(scores={"slow": 64})
    # batch (latency_weight 0.5) tolerates the slow link's transfer
    # cost for the prefix win; interactive (2.0) pays it double and
    # routes to the fast link
    batch = sel.select_worker(
        eps, SchedulingRequest(640, overlap, qos="batch",
                               qos_weight=0.5), 8)
    inter = sel.select_worker(
        eps, SchedulingRequest(640, overlap, qos="interactive",
                               qos_weight=2.0), 8)
    assert batch.worker_id == "slow"
    assert inter.worker_id == "fast"
    assert sel.last_pick["qos"] == "interactive"


# -- per-class series + SLO specs ---------------------------------------------

def test_per_class_histograms_feed_rollup_series_and_slo_specs():
    from dynamo_tpu.observability.fleet import FleetRollup
    from dynamo_tpu.observability.serving import SERVING
    from dynamo_tpu.observability.slo import SloWatchdog, qos_slo_specs
    from dynamo_tpu.observability.timeseries import SeriesStore

    SERVING.reset()
    try:
        for _ in range(6):
            SERVING.ttft.observe("m", "interactive", value=0.02)
            # past the batch class's 20s TTFT target (and inside the
            # bucket ladder, so the quantile can express it)
            SERVING.ttft.observe("m", "batch", value=28.0)
            SERVING.itl.observe("m", "batch", value=0.01)
        SERVING.queue_wait.observe("batch", value=0.5)

        class _Client:
            async def scrape_stats(self):
                return {}

        store = SeriesStore(interval_s=1.0, capacity=64)
        rollup = FleetRollup(_Client(), store=store, interval_s=1.0)
        for t in (100.0, 101.0, 102.0):
            asyncio.run(rollup.scrape_once(ts=t))
        assert store.get("qos/interactive/ttft_p95").latest() < 0.1
        assert store.get("qos/batch/ttft_p95").latest() > 1.0
        assert store.get("qos/batch/itl_p99") is not None
        assert store.get("qos/batch/queue_wait_p95") is not None
        assert "batch" in rollup.summary(ts=102.0)["qos"]

        # per-class specs evaluate those series; batch (4s TTFT vs a
        # 0.5s-target interactive spec untouched) fires its own alert
        specs = qos_slo_specs(short_window_s=2.0, long_window_s=3.0,
                              min_samples=2)
        names = {s.name for s in specs}
        assert {"ttft_p95/interactive", "ttft_p95/batch",
                "itl_p99/batch"} <= names
        assert all(s.degraded_exempt for s in specs)
        wd = SloWatchdog(store, specs, degraded_fn=lambda: False)
        events = wd.evaluate(102.0)
        fired = {e["slo"] for e in events if e["event"] == "fire"}
        assert "ttft_p95/batch" in fired
        assert "ttft_p95/interactive" not in fired
    finally:
        SERVING.reset()


# -- storm replay --------------------------------------------------------------

def test_qos_storm_replay_matches_committed_artifact():
    """The committed QOS_r14.json evidence replays bit-identically:
    the same TenantShape through the real QoS machinery yields the
    exact decision/victim timeline and per-class outcomes."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_storm import TenantShape, qos_storm_once
    path = os.path.join(REPO, "QOS_r14.json")
    if not os.path.exists(path):
        pytest.skip("QOS_r14.json not committed")
    with open(path) as f:
        plan = json.load(f)
    assert plan["ok"] is True
    shape = TenantShape.from_dict(plan["shape"])
    replay = qos_storm_once(shape, True, ticks=plan["ticks"])
    committed = plan["qos"]
    assert replay["timeline"] == committed["timeline"]
    assert replay["per_class"] == committed["per_class"]
    assert replay["aging_promotions"] == committed["aging_promotions"]
    # the committed contracts hold as stated
    assert plan["contracts"]["interactive_p99_held"]
    assert plan["contracts"]["batch_not_starved"]
    assert plan["contracts"]["zero_dropped_streams"]
    assert plan["contracts"]["per_class_slo_fired_and_cleared"]
