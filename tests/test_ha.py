"""Control-plane HA: hot-standby replication, promotion, client failover.

VERDICT r3 missing #3: the reference inherits HA from raft-replicated etcd
and clustered JetStream; our single-binary control plane gains a hot
standby that bootstraps from the primary's snapshot, streams its journal
records, promotes itself when the replication link drops, and serves the
same durable state — with clients following the primary across the pair
(runtime/transports/server.py standby_of, tcp.ControlPlaneClient addrs).
"""
import asyncio

import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.server import ControlPlaneServer
from dynamo_tpu.runtime.transports.tcp import ControlPlaneClient


def run(coro):
    return asyncio.run(coro)


async def wait_for(cond, timeout=10.0, what="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError(what)
        await asyncio.sleep(0.05)


def test_standby_replicates_promotes_and_serves(tmp_path):
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        rt = await DistributedRuntime.connect("127.0.0.1", primary.port, "w")
        await rt.kv.put("models/m1", b"card1")
        for i in range(3):
            await rt.messaging.queue_push("prefill", f"job{i}".encode())

        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="standby sync")
        assert standby.role == "standby"

        # writes AFTER the snapshot ride the record stream
        await rt.kv.put("models/m2", b"card2")
        assert await rt.messaging.queue_pop("prefill", 1.0) == b"job0"
        await wait_for(
            lambda: "models/m2" in standby.plane.kv._data, what="stream kv")
        await wait_for(
            lambda: standby.plane.messaging._queues["prefill"].qsize() == 2,
            what="stream qpop")

        # a standby refuses client ops (clients must follow the primary)
        with pytest.raises(ConnectionError):
            await ControlPlaneClient(
                "127.0.0.1", standby.port).connect(timeout_s=0.6)

        # primary dies -> standby promotes itself
        await rt.shutdown()
        await primary.stop()
        await wait_for(lambda: standby.role == "primary", what="promotion")

        # failover: a client given BOTH addresses lands on the survivor
        # and sees the full durable state (snapshot + streamed records)
        rt2 = await DistributedRuntime.connect(
            "127.0.0.1", 0, "w2",
            addrs=[("127.0.0.1", primary.port),
                   ("127.0.0.1", standby.port)])
        assert await rt2.kv.get("models/m1") == b"card1"
        assert await rt2.kv.get("models/m2") == b"card2"
        assert await rt2.messaging.queue_pop("prefill", 1.0) == b"job1"
        # the promoted plane serves writes, and they are journaled
        await rt2.kv.put("models/m3", b"card3")
        await rt2.messaging.queue_push("prefill", b"job3")
        await rt2.shutdown()
        await standby.stop()

        # the promoted standby's OWN journal is complete: restart from its
        # data dir and everything survives
        reborn = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b")).start()
        rt3 = await DistributedRuntime.connect("127.0.0.1", reborn.port, "w3")
        assert await rt3.kv.get("models/m1") == b"card1"
        assert await rt3.kv.get("models/m3") == b"card3"
        assert await rt3.messaging.queue_depth("prefill") == 2  # job2, job3
        assert await rt3.messaging.queue_pop("prefill", 1.0) == b"job2"
        await rt3.shutdown()
        await reborn.stop()

    run(main())


def test_comma_addr_form_and_mid_failover_retry(tmp_path):
    """The DYN_COORD_ADDR comma form parses, and a client connecting
    DURING the failover window (primary down, standby not yet promoted)
    rides it out via the retry loop."""
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        rt = await DistributedRuntime.connect("127.0.0.1", primary.port, "w")
        await rt.kv.put("k", b"v")
        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="sync")
        p_port, s_port = primary.port, standby.port
        await rt.shutdown()
        # start the failover-window client BEFORE stopping the primary is
        # racy to arrange exactly; instead connect concurrently with the
        # stop+promotion so some probes hit the standby pre-promotion
        async def failover_connect():
            return await DistributedRuntime.connect(
                f"127.0.0.1:{p_port},127.0.0.1:{s_port}", 0, "w2")

        task = asyncio.create_task(failover_connect())
        await primary.stop()
        rt2 = await asyncio.wait_for(task, 30)
        assert await rt2.kv.get("k") == b"v"
        await rt2.shutdown()
        await standby.stop()

    run(main())
