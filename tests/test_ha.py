"""Control-plane HA: hot-standby replication, promotion, client failover.

VERDICT r3 missing #3: the reference inherits HA from raft-replicated etcd
and clustered JetStream; our single-binary control plane gains a hot
standby that bootstraps from the primary's snapshot, streams its journal
records, promotes itself when the replication link drops, and serves the
same durable state — with clients following the primary across the pair
(runtime/transports/server.py standby_of, tcp.ControlPlaneClient addrs).
"""
import asyncio

import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.server import ControlPlaneServer
from dynamo_tpu.runtime.transports.tcp import ControlPlaneClient


def run(coro):
    return asyncio.run(coro)


async def wait_for(cond, timeout=10.0, what="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError(what)
        await asyncio.sleep(0.05)


def test_standby_replicates_promotes_and_serves(tmp_path):
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        rt = await DistributedRuntime.connect("127.0.0.1", primary.port, "w")
        await rt.kv.put("models/m1", b"card1")
        for i in range(3):
            await rt.messaging.queue_push("prefill", f"job{i}".encode())

        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="standby sync")
        assert standby.role == "standby"

        # writes AFTER the snapshot ride the record stream
        await rt.kv.put("models/m2", b"card2")
        assert await rt.messaging.queue_pop("prefill", 1.0) == b"job0"
        await wait_for(
            lambda: "models/m2" in standby.plane.kv._data, what="stream kv")
        await wait_for(
            lambda: standby.plane.messaging._queues["prefill"].qsize() == 2,
            what="stream qpop")

        # a standby refuses client ops (clients must follow the primary)
        with pytest.raises(ConnectionError):
            await ControlPlaneClient(
                "127.0.0.1", standby.port).connect(timeout_s=0.6)

        # primary dies -> standby promotes itself
        await rt.shutdown()
        await primary.stop()
        await wait_for(lambda: standby.role == "primary", what="promotion")

        # failover: a client given BOTH addresses lands on the survivor
        # and sees the full durable state (snapshot + streamed records)
        rt2 = await DistributedRuntime.connect(
            "127.0.0.1", 0, "w2",
            addrs=[("127.0.0.1", primary.port),
                   ("127.0.0.1", standby.port)])
        assert await rt2.kv.get("models/m1") == b"card1"
        assert await rt2.kv.get("models/m2") == b"card2"
        assert await rt2.messaging.queue_pop("prefill", 1.0) == b"job1"
        # the promoted plane serves writes, and they are journaled
        await rt2.kv.put("models/m3", b"card3")
        await rt2.messaging.queue_push("prefill", b"job3")
        await rt2.shutdown()
        await standby.stop()

        # the promoted standby's OWN journal is complete: restart from its
        # data dir and everything survives
        reborn = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b")).start()
        rt3 = await DistributedRuntime.connect("127.0.0.1", reborn.port, "w3")
        assert await rt3.kv.get("models/m1") == b"card1"
        assert await rt3.kv.get("models/m3") == b"card3"
        assert await rt3.messaging.queue_depth("prefill") == 2  # job2, job3
        assert await rt3.messaging.queue_pop("prefill", 1.0) == b"job2"
        await rt3.shutdown()
        await reborn.stop()

    run(main())


def test_partition_fencing_no_divergent_acks(tmp_path):
    """VERDICT r4 missing #4 / ADVICE r4 medium: a partition between the
    pair must not yield two primaries silently accepting divergent writes.
    Sever ONLY the replication link (both members stay up and reachable —
    the dual-primary scenario): the standby promotes at a bumped epoch,
    clients pick the higher-epoch primary, the promoted side's fencing
    loop deposes the stale one, and the deposed member refuses every op —
    so acknowledged writes never interleave across the two."""
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        c1 = await ControlPlaneClient("127.0.0.1", primary.port).connect()
        await c1.put("k", b"v1")
        assert c1.epoch == 1 and primary.epoch == 1

        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="standby sync")

        # PARTITION: the standby can no longer reach the primary AT ALL
        # (probe-before-promote sees it as dead), but the primary keeps
        # serving c1 and stays reachable for clients — the asymmetric
        # split that yields two self-claimed primaries
        async def _unreachable(host, port):
            return False
        standby._primary_alive = _unreachable
        for _sid, (_q, conn) in list(primary.repl_subs.items()):
            conn.writer.close()
        await wait_for(lambda: standby.role == "primary", what="promotion")
        assert standby.epoch == 2

        # a fresh client that can reach BOTH self-claimed primaries
        # enrolls with the higher epoch — never the stale side
        both = [("127.0.0.1", primary.port), ("127.0.0.1", standby.port)]
        c2 = await ControlPlaneClient(addrs=both).connect()
        assert c2.port == standby.port and c2.epoch == 2
        await c2.put("k", b"v2")

        # the promoted side's fencing loop reaches the old primary
        # (reachable here — the "healed" case): it steps down AND rejoins
        # as the winner's hot standby (self-healing pair), re-syncing to
        # the epoch-2 history
        await wait_for(lambda: primary.role != "primary", timeout=15,
                       what="old primary deposed")
        assert primary.epoch == 2
        await wait_for(lambda: primary.role == "standby" and primary.synced,
                       timeout=15, what="old primary rejoined as standby")
        await wait_for(
            lambda: primary.plane.kv._data.get("k") is not None
            and primary.plane.kv._data["k"].value == b"v2",
            what="rejoined standby re-synced")

        # the stale-enrolled client's writes are now REFUSED, not
        # acknowledged into a divergent history
        with pytest.raises((RuntimeError, ConnectionError)):
            await c1.put("k", b"v-stale")

        # an op carrying an older epoch is refused even before deposition
        # semantics: the promoted primary rejects epoch-1 traffic outright
        c2.epoch = 1
        with pytest.raises(RuntimeError, match="stale epoch"):
            await c2.put("k", b"v-old-epoch")
        c2.epoch = 2

        # the stale client reconnects via the pair and lands on the new
        # primary, observing only the epoch-2 history
        await c1.close()
        c1b = await ControlPlaneClient(addrs=both).connect()
        assert c1b.port == standby.port and c1b.epoch == 2
        assert await c1b.get("k") == b"v2"

        # a member that RESTARTS from its data dir comes back as primary
        # at its old epoch — and is re-fenced by the survivor's loop into
        # a standby again, so it can never re-enter service stale
        p_port = primary.port
        await primary.stop()
        reborn = await ControlPlaneServer(
            host="127.0.0.1", port=p_port,
            data_dir=str(tmp_path / "a")).start()
        assert reborn.epoch <= 2  # pre-rejoin journal state
        await wait_for(lambda: reborn.role == "standby" and reborn.synced,
                       timeout=15,
                       what="reborn stale primary re-fenced to standby")

        await c1b.close()
        await c2.close()
        await reborn.stop()
        await standby.stop()

    run(main())


def test_promoted_member_refuses_stale_snapshot_and_resumes_primacy(
        tmp_path):
    """Failback path (code-review r5): after B promoted at epoch 2 and
    acknowledged writes, restarting B as --standby-of a STALE primary A
    (still at epoch 1) must not wipe B's newer history with A's snapshot.
    B refuses the stale snapshot, resumes primacy at its journaled epoch,
    and fences A."""
    async def main():
        a = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        c = await ControlPlaneClient("127.0.0.1", a.port).connect()
        await c.put("k", b"v1")
        b = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", a.port)).start()
        await wait_for(lambda: b.synced, what="sync")

        # partition (standby cannot reach A, nor can its fencing traffic)
        # -> B promotes at epoch 2
        async def _unreachable(host, port):
            return False

        async def _no_fence(host, port):
            await asyncio.Event().wait()

        b._primary_alive = _unreachable
        b._fence_peer = _no_fence
        for _sid, (_q, conn) in list(a.repl_subs.items()):
            conn.writer.close()
        await wait_for(lambda: b.role == "primary", what="promotion")

        # an epoch-2 acknowledged write lands on B, then B dies
        c2 = await ControlPlaneClient("127.0.0.1", b.port).connect()
        assert c2.epoch == 2
        await c2.put("k", b"v2-acked")
        await c2.close()
        await b.stop()
        assert a.role == "primary"  # the stale side never learned

        # B restarts pointed at stale A: must refuse A's epoch-1 snapshot,
        # resume primacy at epoch 2 with its history intact, and fence A
        b2 = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", a.port)).start()
        await wait_for(lambda: b2.role == "primary", timeout=15,
                       what="resume primacy")
        assert b2.epoch == 2
        # the stale primary A is fenced and self-heals into B's standby,
        # re-synced to the epoch-2 history (its divergent tail discarded)
        await wait_for(lambda: a.role == "standby" and a.synced,
                       timeout=15, what="stale primary fenced to standby")
        await wait_for(
            lambda: a.plane.kv._data.get("k") is not None
            and a.plane.kv._data["k"].value == b"v2-acked",
            what="rejoined standby holds the winner's history")
        c3 = await ControlPlaneClient("127.0.0.1", b2.port).connect()
        assert c3.epoch == 2
        assert await c3.get("k") == b"v2-acked"

        await c.close()
        await c3.close()
        await a.stop()
        await b2.stop()

    run(main())


def test_evicted_standby_rebootstraps_without_promoting(tmp_path):
    """A standby that falls behind the bounded replication queue is
    evicted (connection closed by the primary). Because the primary is
    still alive and answering, the standby's probe-before-promote must
    re-bootstrap it from a fresh snapshot — NOT promote it onto a replica
    missing records (code-review r5: eviction must not trigger failover
    and then fence the healthy primary)."""
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        c1 = await ControlPlaneClient("127.0.0.1", primary.port).connect()
        await c1.put("k", b"v1")
        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="standby sync")

        # overflow the subscriber's bounded queue in one synchronous
        # burst (no awaits, so the pump can't drain), then deliver one
        # more record -> eviction
        sid, (q, _conn) = next(iter(primary.repl_subs.items()))
        while True:
            try:
                q.put_nowait({"op": "noop"})
            except asyncio.QueueFull:
                break
        primary._fanout_record({"op": "put", "key": "x", "value": b"y"})
        assert sid not in primary.repl_subs

        # the standby re-bootstraps: fresh subscription, still a standby
        await wait_for(lambda: standby.synced
                       and len(primary.repl_subs) == 1
                       and sid not in primary.repl_subs,
                       what="re-bootstrap")
        assert standby.role == "standby" and standby.epoch == 1
        assert primary.role == "primary"

        # replication works again end-to-end after the re-bootstrap
        await c1.put("k2", b"v2")
        await wait_for(lambda: "k2" in standby.plane.kv._data,
                       what="stream after re-bootstrap")

        await c1.close()
        await standby.stop()
        await primary.stop()

    run(main())


def test_comma_addr_form_and_mid_failover_retry(tmp_path):
    """The DYN_COORD_ADDR comma form parses, and a client connecting
    DURING the failover window (primary down, standby not yet promoted)
    rides it out via the retry loop."""
    async def main():
        primary = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "a")).start()
        rt = await DistributedRuntime.connect("127.0.0.1", primary.port, "w")
        await rt.kv.put("k", b"v")
        standby = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "b"),
            standby_of=("127.0.0.1", primary.port)).start()
        await wait_for(lambda: standby.synced, what="sync")
        p_port, s_port = primary.port, standby.port
        await rt.shutdown()
        # start the failover-window client BEFORE stopping the primary is
        # racy to arrange exactly; instead connect concurrently with the
        # stop+promotion so some probes hit the standby pre-promotion
        async def failover_connect():
            return await DistributedRuntime.connect(
                f"127.0.0.1:{p_port},127.0.0.1:{s_port}", 0, "w2")

        task = asyncio.create_task(failover_connect())
        await primary.stop()
        rt2 = await asyncio.wait_for(task, 30)
        assert await rt2.kv.get("k") == b"v"
        await rt2.shutdown()
        await standby.stop()

    run(main())
