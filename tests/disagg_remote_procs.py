"""Role scripts for the TRUE two-process disaggregation test.

Spawned by tests/test_remote_transfer.py with a shared standalone
control-plane server: one process runs the decode worker (+ KvTransferServer
registered in the discovery KV), the other runs the prefill worker (+
RemoteTransferBackend). KV pages cross a real process boundary over TCP —
the reference's NIXL role (SURVEY.md §2.7), exercised the way its disagg
example deploys (separate engine processes, examples/llm/graphs).

Usage: python tests/disagg_remote_procs.py {decode|prefill} <control_port>
"""
import asyncio
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.disagg import (  # noqa: E402
    DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer, PrefillQueue,
    PrefillWorker, RemoteTransferBackend,
)
from dynamo_tpu.engine.config import EngineConfig, ModelConfig  # noqa: E402
from dynamo_tpu.engine.engine import NativeEngine  # noqa: E402
from dynamo_tpu.llm.worker import (  # noqa: E402
    NativeEngineWorker, serve_llm_worker,
)
from dynamo_tpu.parallel.mesh import make_mesh  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine(mesh=None):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), mesh=mesh, seed=0)


async def decode_main(port: int) -> None:
    rt = await DistributedRuntime.connect("127.0.0.1", port,
                                          worker_id="dec-0")
    queue = PrefillQueue(rt.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=8, model="tiny")
    worker = DisaggDecodeWorker(
        make_engine(), rt.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=60.0)
    await worker.start()
    server = await KvTransferServer(worker, "dec-0").start()
    await server.register(rt.kv, rt.lease.id)
    await serve_llm_worker(rt, "ns", "decoder", worker)
    print("READY decode", flush=True)
    await rt.shutdown_event.wait()


async def prefill_main(port: int) -> None:
    rt = await DistributedRuntime.connect("127.0.0.1", port,
                                          worker_id="pre-0")
    queue = PrefillQueue(rt.messaging, "ns", "tiny")
    # tp=2 mesh: the prefill cache layout differs from decode's tp=1 —
    # the transfer's device_put reshard covers the kv_rearrange role
    mesh = make_mesh(tp=2, devices=jax.devices()[:2])
    transfer = RemoteTransferBackend(rt.kv, chunk_pages=2)
    worker = PrefillWorker(NativeEngineWorker(make_engine(mesh)), queue,
                           transfer, rt.messaging)
    await worker.start()
    print("READY prefill", flush=True)
    await rt.shutdown_event.wait()


if __name__ == "__main__":
    role, port = sys.argv[1], int(sys.argv[2])
    main = decode_main if role == "decode" else prefill_main
    try:
        asyncio.run(main(port))
    except KeyboardInterrupt:
        pass
