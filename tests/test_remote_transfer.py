"""Cross-process KV transfer tests (the NIXL-equivalent, VERDICT.md item 2).

Layers of coverage:
1. In-process over real TCP sockets: KvTransferServer + RemoteTransferBackend
   replace LocalTransferBackend in the full disagg worker flow — exact-output
   parity with an aggregated engine, tp-mismatch relayout, chunked frames.
2. Rejection race: decode released the allocation (timeout path) before the
   transfer lands — the inject must be refused.
3. TRUE two-process: decode worker and prefill worker in separate OS
   processes joined only by the standalone control-plane server; pages cross
   a real process boundary; exact parity with the in-test aggregated oracle.
"""
import asyncio
import os
import signal
import socket
import subprocess
import sys

import jax
import pytest

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer, PrefillQueue,
    PrefillWorker, RemoteTransferBackend,
)
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.llm.worker import NativeEngineWorker
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_engine(mesh=None, kv_quant=""):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512,
        kv_quant=kv_quant), mesh=mesh, seed=0)


def pre_request(rid, prompt, max_tokens=6):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True))


async def _drive(worker_gen):
    toks, reason = [], None
    async for frame in worker_gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reason = frame["finish_reason"]
    return toks, reason


async def _build_remote_stack(plane, decode_mesh=None, prefill_mesh=None,
                              chunk_pages=16, kv_quant=""):
    """Disagg stack wired through the REMOTE transfer path over TCP."""
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=8, model="tiny")
    decode = DisaggDecodeWorker(
        make_engine(decode_mesh, kv_quant), plane.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=30.0)
    server = await KvTransferServer(decode, "dec-0").start()
    await server.register(plane.kv)
    transfer = RemoteTransferBackend(plane.kv, chunk_pages=chunk_pages)
    prefill = PrefillWorker(
        NativeEngineWorker(make_engine(prefill_mesh, kv_quant)), queue,
        transfer, plane.messaging)
    return decode, prefill, server, transfer


def test_remote_transfer_e2e_matches_aggregated():
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(plane)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("r1", prompt).model_dump(
                    exclude_none=True), Context("r1")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return (toks, reason, decode.remote_prefills, prefill.completed,
                server.received_pages, transfer.sent_pages)

    toks, reason, n_remote, n_done, rx, tx = asyncio.run(main())
    assert n_remote == 1 and n_done == 1
    assert rx == tx == 3  # 20 tokens / page 8 -> 3 pages crossed the wire
    assert reason == "length"
    assert toks == expect


def test_remote_transfer_kv_quant_int8_halves_wire_bytes():
    """int8-KV engines on both sides: frames carry int8 pages + f32
    scale rows, tokens match the int8 aggregated oracle, and the wire
    payload per page is ~half the bf16-equivalent — the acceptance
    bar's disagg-transfer leg (~2x fewer bytes per handoff)."""
    from dynamo_tpu.ops.kv_quant import page_bytes
    from dynamo_tpu.runtime.integrity import XFER_STATS
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine(kv_quant="int8").generate(prompt, params, "direct")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, kv_quant="int8")
        await decode.start()
        await prefill.start()
        b0, p0 = XFER_STATS.bytes_sent, XFER_STATS.pages_sent
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("rq", prompt).model_dump(
                    exclude_none=True), Context("rq")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return (toks, reason, server.received_pages, transfer.sent_pages,
                XFER_STATS.bytes_sent - b0, XFER_STATS.pages_sent - p0)

    toks, reason, rx, tx, bytes_sent, pages_sent = asyncio.run(main())
    assert rx == tx == 3 and reason == "length"
    assert toks == expect
    # wire bytes per page (pow2 padding included) stay well under the
    # bf16 page's footprint: >= 1.8x fewer bytes per handoff
    mc = CFG
    bf16_pb = page_bytes(mc.num_layers, mc.num_kv_heads, PAGE,
                         mc.head_dim, 4, False)  # f32 test dtype
    int8_pb = page_bytes(mc.num_layers, mc.num_kv_heads, PAGE,
                         mc.head_dim, 4, True)
    assert pages_sent >= 3
    # 3 real pages padded to a pow2-4 frame: compare against the padded
    # count so the bound is honest about what crossed the wire
    assert bytes_sent <= 4 * int8_pb
    assert bf16_pb / int8_pb >= 1.8


def test_remote_transfer_chunked_and_tp_mismatch():
    """chunk_pages=1 forces one frame per page; prefill tp=2 vs decode tp=1
    exercises the device_put relayout on receive."""
    devs = jax.devices()
    assert len(devs) >= 2
    prefill_mesh = make_mesh(tp=2, devices=devs[:2])
    prompt = list(range(60, 80))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, prefill_mesh=prefill_mesh, chunk_pages=1)
        await decode.start()
        await prefill.start()
        try:
            toks, _ = await _drive(
                decode.generate(pre_request("t1", prompt).model_dump(
                    exclude_none=True), Context("t1")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, decode.remote_prefills, server.received_pages

    toks, n_remote, rx = asyncio.run(main())
    assert n_remote == 1 and rx == 3
    assert toks == expect


def test_remote_inject_rejected_after_release():
    """Decode timed out and released the allocation: a late transfer must be
    refused (injecting would corrupt reallocated pages)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    async def main():
        plane = MemoryPlane()
        decode = NativeEngineWorker(make_engine())
        await decode.start()
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        # 1-page chunks + a 3-deep window: the rejection arrives while two
        # acks are still unread, exercising the connection-drop-on-reject
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                         window_chunks=3)
        prefill_eng = make_engine()
        try:
            alloc = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            assert alloc is not None
            # prefill runs and extracts pages
            prefill_eng.add_request(
                EngineRequest("race", prompt, params, prefill_only=True))
            while prefill_eng.has_work():
                prefill_eng.step()
            pages = prefill_eng.extract_pages(
                prefill_eng.scheduler.parked["race"].pages)
            # decode gives up (timeout path) BEFORE the transfer lands
            await decode.submit(lambda eng: eng.release_remote("race"))
            with pytest.raises(RuntimeError, match="no longer pending"):
                await transfer.send_pages("dec-0", "race", alloc.page_ids,
                                          pages["k"], pages["v"])
            # the rejection must not poison the pooled connection: with the
            # pipelining window, unread acks left on the socket would
            # desync the NEXT transfer's ack accounting (code-review r3).
            # A fresh request through the same backend must succeed.
            alloc2 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("ok", prompt, params)))
            await transfer.send_pages("dec-0", "ok", alloc2.page_ids,
                                      pages["k"], pages["v"])
            assert transfer.sent_pages == len(alloc2.page_ids)
        finally:
            await transfer.close()
            await server.stop()
            await decode.stop()
        return server.received_pages

    # the rejected transfer must inject NOTHING; the follow-up "ok"
    # transfer injects its 3 pages
    assert asyncio.run(main()) == 3


def test_transfer_pipelining_overlaps_chunks():
    """The sender must keep multiple chunks in flight: this fake decode
    endpoint withholds ALL acks until it has received 2 frames — a
    stop-and-wait sender deadlocks (times out) here, a windowed sender
    streams through (VERDICT r2 weak #4: pipelined transfer)."""
    import numpy as np

    import msgpack

    from dynamo_tpu.disagg.remote_transfer import transfer_key
    from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

    async def main():
        plane = MemoryPlane()
        received = []

        async def on_connect(reader, writer):
            pending = 0
            try:
                while True:
                    try:
                        frame = await read_frame(reader)
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        return
                    received.append(len(frame["page_ids"]))
                    pending += 1
                    if len(received) >= 2:
                        for _ in range(pending):
                            write_frame(writer, {"ok": True})
                        await writer.drain()
                        pending = 0
            finally:
                # 3.12 Server.wait_closed() waits for every connection;
                # an unclosed writer would hang the test teardown
                writer.close()

        server = await asyncio.start_server(on_connect, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        await plane.kv.put(
            transfer_key("fake"),
            msgpack.packb({"host": "127.0.0.1", "port": port},
                          use_bin_type=True))
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                         window_chunks=3)
        z = np.zeros((2, 2, 4, 8, 4), np.float32)  # 4 pages -> 4 frames
        await asyncio.wait_for(
            transfer.send_pages("fake", "r", [0, 1, 2, 3], z, z), 10)
        assert transfer.sent_pages == 4
        assert received == [1, 1, 1, 1]
        await transfer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(main())


def test_remote_transfer_metadata_missing():
    """Unknown engine_id (worker lease gone): clear error, no hang."""
    async def main():
        plane = MemoryPlane()
        transfer = RemoteTransferBackend(plane.kv)
        import numpy as np
        z = np.zeros((2, 2, 1, 8, 32), np.float32)
        with pytest.raises(KeyError, match="no kv-transfer metadata"):
            await transfer.send_pages("ghost", "r", [0], z, z)

    asyncio.run(main())


# -- TRUE two-process disaggregation ------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(args, env):
    return subprocess.Popen(
        [sys.executable] + args, stdout=subprocess.PIPE, cwd=REPO, env=env,
        text=True)


def _wait_ready(proc, tag, deadline=120):
    line = proc.stdout.readline()
    assert line, f"{tag} exited before READY"
    assert line.startswith("READY"), f"{tag} said {line!r}"


def test_disagg_two_processes_exact_parity():
    """Decode and prefill engines in SEPARATE OS processes; KV pages cross a
    real process boundary over the transfer plane; output matches the
    aggregated single-engine oracle exactly (VERDICT item 2 'Done' bar)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "oracle")

    port = _free_port()
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    cp = _spawn(["-m", "dynamo_tpu.runtime.transports.server",
                 "--port", str(port)], env)
    decode = prefill = None
    try:
        # give the control-plane server a moment to bind
        deadline = 50
        for _ in range(deadline * 10):
            try:
                s = socket.create_connection(("127.0.0.1", port), 0.2)
                s.close()
                break
            except OSError:
                import time
                time.sleep(0.1)
        decode = _spawn(["tests/disagg_remote_procs.py", "decode",
                         str(port)], env)
        prefill = _spawn(["tests/disagg_remote_procs.py", "prefill",
                          str(port)], env)
        _wait_ready(decode, "decode")
        _wait_ready(prefill, "prefill")

        async def drive():
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            rt = await DistributedRuntime.connect("127.0.0.1", port)
            client = rt.namespace("ns").component("decoder").endpoint(
                "generate").client()
            await client.start()
            await client.wait_for_instances()
            toks = []
            req = pre_request("two-proc", prompt).model_dump(
                exclude_none=True)
            async for frame in await client.generate(req):
                toks.extend(frame.get("token_ids", ()))
            await client.stop()
            await rt.shutdown()
            return toks

        toks = asyncio.run(asyncio.wait_for(drive(), 180))
        assert toks == expect
    finally:
        for p in (decode, prefill, cp):
            if p is not None:
                p.send_signal(signal.SIGINT)
        for p in (decode, prefill, cp):
            if p is not None:
                try:
                    p.wait(15)
                except subprocess.TimeoutExpired:
                    p.kill()
