"""Cross-process KV transfer tests (the NIXL-equivalent, VERDICT.md item 2).

Layers of coverage:
1. In-process over real TCP sockets: KvTransferServer + RemoteTransferBackend
   replace LocalTransferBackend in the full disagg worker flow — exact-output
   parity with an aggregated engine, tp-mismatch relayout, chunked frames.
2. Rejection race: decode released the allocation (timeout path) before the
   transfer lands — the inject must be refused.
3. TRUE two-process: decode worker and prefill worker in separate OS
   processes joined only by the standalone control-plane server; pages cross
   a real process boundary; exact parity with the in-test aggregated oracle.
"""
import asyncio
import os
import signal
import socket
import subprocess
import sys

import jax
import pytest

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer, PrefillQueue,
    PrefillWorker, RemoteTransferBackend,
)
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.llm.worker import NativeEngineWorker
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_engine(mesh=None, kv_quant=""):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512,
        kv_quant=kv_quant), mesh=mesh, seed=0)


# ONE oracle engine per (kv_quant mode) for the module (tier-1 budget):
# oracle generation is deterministic and prefix reuse is exact, so
# sharing it across tests only warms its cache.
_ORACLE = {}
_EXPECT = {}


def expected(prompt, params, kv_quant=""):
    key = (tuple(prompt), params.max_tokens, params.temperature,
           params.seed, kv_quant)
    if key not in _EXPECT:
        eng = _ORACLE.get(kv_quant)
        if eng is None:
            eng = _ORACLE[kv_quant] = make_engine(kv_quant=kv_quant)
        _EXPECT[key] = eng.generate(prompt, params, f"o{len(_EXPECT)}")
    return _EXPECT[key]


def pre_request(rid, prompt, max_tokens=6):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True))


async def _drive(worker_gen):
    toks, reason = [], None
    async for frame in worker_gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reason = frame["finish_reason"]
    return toks, reason


async def _build_remote_stack(plane, decode_mesh=None, prefill_mesh=None,
                              chunk_pages=16, kv_quant=""):
    """Disagg stack wired through the REMOTE transfer path over TCP."""
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=8, model="tiny")
    decode = DisaggDecodeWorker(
        make_engine(decode_mesh, kv_quant), plane.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=30.0)
    server = await KvTransferServer(decode, "dec-0").start()
    await server.register(plane.kv)
    transfer = RemoteTransferBackend(plane.kv, chunk_pages=chunk_pages)
    prefill = PrefillWorker(
        NativeEngineWorker(make_engine(prefill_mesh, kv_quant)), queue,
        transfer, plane.messaging)
    return decode, prefill, server, transfer


def test_remote_transfer_e2e_matches_aggregated():
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(plane)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("r1", prompt).model_dump(
                    exclude_none=True), Context("r1")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return (toks, reason, decode.remote_prefills, prefill.completed,
                server.received_pages, transfer.sent_pages)

    toks, reason, n_remote, n_done, rx, tx = asyncio.run(main())
    assert n_remote == 1 and n_done == 1
    assert rx == tx == 3  # 20 tokens / page 8 -> 3 pages crossed the wire
    assert reason == "length"
    assert toks == expect


def test_remote_transfer_kv_quant_int8_halves_wire_bytes():
    """int8-KV engines on both sides: frames carry int8 pages + f32
    scale rows, tokens match the int8 aggregated oracle, and the wire
    payload per page is ~half the bf16-equivalent — the acceptance
    bar's disagg-transfer leg (~2x fewer bytes per handoff)."""
    from dynamo_tpu.ops.kv_quant import page_bytes
    from dynamo_tpu.runtime.integrity import XFER_STATS
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params, kv_quant="int8")

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, kv_quant="int8")
        await decode.start()
        await prefill.start()
        b0, p0 = XFER_STATS.bytes_sent, XFER_STATS.pages_sent
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("rq", prompt).model_dump(
                    exclude_none=True), Context("rq")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return (toks, reason, server.received_pages, transfer.sent_pages,
                XFER_STATS.bytes_sent - b0, XFER_STATS.pages_sent - p0)

    toks, reason, rx, tx, bytes_sent, pages_sent = asyncio.run(main())
    assert rx == tx == 3 and reason == "length"
    assert toks == expect
    # wire bytes per page (pow2 padding included) stay well under the
    # bf16 page's footprint: >= 1.8x fewer bytes per handoff
    mc = CFG
    bf16_pb = page_bytes(mc.num_layers, mc.num_kv_heads, PAGE,
                         mc.head_dim, 4, False)  # f32 test dtype
    int8_pb = page_bytes(mc.num_layers, mc.num_kv_heads, PAGE,
                         mc.head_dim, 4, True)
    assert pages_sent >= 3
    # 3 real pages padded to a pow2-4 frame: compare against the padded
    # count so the bound is honest about what crossed the wire
    assert bytes_sent <= 4 * int8_pb
    assert bf16_pb / int8_pb >= 1.8


def test_remote_transfer_chunked_and_tp_mismatch():
    """chunk_pages=1 forces one frame per page; prefill tp=2 vs decode tp=1
    exercises the device_put relayout on receive."""
    devs = jax.devices()
    assert len(devs) >= 2
    prefill_mesh = make_mesh(tp=2, devices=devs[:2])
    prompt = list(range(60, 80))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, prefill_mesh=prefill_mesh, chunk_pages=1)
        await decode.start()
        await prefill.start()
        try:
            toks, _ = await _drive(
                decode.generate(pre_request("t1", prompt).model_dump(
                    exclude_none=True), Context("t1")))
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, decode.remote_prefills, server.received_pages

    toks, n_remote, rx = asyncio.run(main())
    assert n_remote == 1 and rx == 3
    assert toks == expect


def test_remote_inject_rejected_after_release():
    """Decode timed out and released the allocation: a late transfer must be
    refused (injecting would corrupt reallocated pages)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    async def main():
        plane = MemoryPlane()
        decode = NativeEngineWorker(make_engine())
        await decode.start()
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        # 1-page chunks + a 3-deep window: the rejection arrives while two
        # acks are still unread, exercising the connection-drop-on-reject
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                         window_chunks=3)
        prefill_eng = make_engine()
        try:
            alloc = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            assert alloc is not None
            # prefill runs and extracts pages
            prefill_eng.add_request(
                EngineRequest("race", prompt, params, prefill_only=True))
            while prefill_eng.has_work():
                prefill_eng.step()
            pages = prefill_eng.extract_pages(
                prefill_eng.scheduler.parked["race"].pages)
            # decode gives up (timeout path) BEFORE the transfer lands
            await decode.submit(lambda eng: eng.release_remote("race"))
            with pytest.raises(RuntimeError, match="no longer pending"):
                await transfer.send_pages("dec-0", "race", alloc.page_ids,
                                          pages["k"], pages["v"])
            # the rejection must not poison the pooled connection: with the
            # pipelining window, unread acks left on the socket would
            # desync the NEXT transfer's ack accounting (code-review r3).
            # A fresh request through the same backend must succeed.
            alloc2 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("ok", prompt, params)))
            await transfer.send_pages("dec-0", "ok", alloc2.page_ids,
                                      pages["k"], pages["v"])
            assert transfer.sent_pages == len(alloc2.page_ids)
        finally:
            await transfer.close()
            await server.stop()
            await decode.stop()
        return server.received_pages

    # the rejected transfer must inject NOTHING; the follow-up "ok"
    # transfer injects its 3 pages
    assert asyncio.run(main()) == 3


def test_transfer_pipelining_overlaps_chunks():
    """The sender must keep multiple chunks in flight: this fake decode
    endpoint withholds ALL acks until it has received 2 frames — a
    stop-and-wait sender deadlocks (times out) here, a windowed sender
    streams through (VERDICT r2 weak #4: pipelined transfer)."""
    import numpy as np

    import msgpack

    from dynamo_tpu.disagg.remote_transfer import transfer_key
    from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

    async def main():
        plane = MemoryPlane()
        received = []

        async def on_connect(reader, writer):
            pending = 0
            try:
                while True:
                    try:
                        frame = await read_frame(reader)
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        return
                    if frame.get("op") == "resume":
                        # the committed-frontier handshake every stream
                        # opens with; a fresh transfer resumes from 0
                        write_frame(writer, {"ok": True, "committed": 0})
                        await writer.drain()
                        continue
                    received.append(len(frame["page_ids"]))
                    pending += 1
                    if len(received) >= 2:
                        for _ in range(pending):
                            write_frame(writer, {"ok": True})
                        await writer.drain()
                        pending = 0
            finally:
                # 3.12 Server.wait_closed() waits for every connection;
                # an unclosed writer would hang the test teardown
                writer.close()

        server = await asyncio.start_server(on_connect, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        await plane.kv.put(
            transfer_key("fake"),
            msgpack.packb({"host": "127.0.0.1", "port": port},
                          use_bin_type=True))
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                         window_chunks=3)
        z = np.zeros((2, 2, 4, 8, 4), np.float32)  # 4 pages -> 4 frames
        await asyncio.wait_for(
            transfer.send_pages("fake", "r", [0, 1, 2, 3], z, z), 10)
        assert transfer.sent_pages == 4
        assert received == [1, 1, 1, 1]
        await transfer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(main())


def test_remote_transfer_metadata_missing():
    """Unknown engine_id (worker lease gone): clear error, no hang."""
    async def main():
        plane = MemoryPlane()
        transfer = RemoteTransferBackend(plane.kv)
        import numpy as np
        z = np.zeros((2, 2, 1, 8, 32), np.float32)
        with pytest.raises(KeyError, match="no kv-transfer metadata"):
            await transfer.send_pages("ghost", "r", [0], z, z)

    asyncio.run(main())


# -- chunk-committed streaming: the resume matrix ------------------------------
# (docs/RESILIENCE.md "Data-plane transfer failure model")

from dynamo_tpu.disagg.remote_transfer import (  # noqa: E402
    TransferBudgetExceeded,
)
from dynamo_tpu.runtime import faults  # noqa: E402
from dynamo_tpu.runtime.faults import FaultSchedule, FaultSpec  # noqa: E402
from dynamo_tpu.runtime.integrity import XFER_STATS  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()


@pytest.mark.parametrize("cut_chunk", [0, 1, 2])
def test_transfer_link_cut_resumes_token_identical(cut_chunk):
    """Seeded link cut at the first/middle/last chunk: the sender
    reconnects, learns the committed frontier, and resumes — the decode
    side injects every page exactly once and the stream is
    token-identical to the aggregated oracle."""
    prompt = list(range(100, 120))  # 3 pages @ page_size 8 -> 3 chunks
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    # stop-and-wait window: every chunk before the cut is fully acked,
    # so the frontier at the cut is exactly cut_chunk — deterministic
    faults.REGISTRY.arm("transfer.link", FaultSchedule(
        0, [FaultSpec("fail_n", n=1, skip=cut_chunk)]))
    r0 = XFER_STATS.resumes

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, chunk_pages=1)
        transfer.window_chunks = 1
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("rl", prompt).model_dump(
                    exclude_none=True), Context("rl"))), 60)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return toks, reason, server.received_pages, transfer.sent_pages

    toks, reason, rx, tx = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert rx == tx == 3   # every page injected exactly once, all acked
    if cut_chunk > 0:
        # the retry continued a part-committed transfer (a chunk-level
        # resume); a cut before anything committed restarts from zero
        # and is not a resume
        assert XFER_STATS.resumes - r0 == 1
    assert faults.REGISTRY.snapshot()["injected"]["transfer.link"] == 1


def test_sender_death_mid_stream_resumes_from_acked_frontier():
    """The prefill worker dies mid-transfer with chunks already acked:
    the re-leased queue item's REPLACEMENT sender opens with the
    frontier handshake and ships only the unacked tail — no page
    crosses the wire twice, and the stream never notices."""
    prompt = list(range(50, 90))   # 40 tokens -> 5 pages
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    r0 = XFER_STATS.resumes

    class StallAfter(RemoteTransferBackend):
        """Wedges forever at chunk `stall_after`: the worker driving it
        dies holding a part-committed transfer."""

        async def _chunk_gate(self, chunk_idx, stream=0):
            if chunk_idx >= 2:
                await asyncio.Event().wait()
            await super()._chunk_gate(chunk_idx, stream)

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=8, model="tiny")
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=60.0)
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        doomed = PrefillWorker(
            NativeEngineWorker(make_engine()), queue,
            StallAfter(plane.kv, chunk_pages=1, window_chunks=1),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=0.5)
        surv_tx = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                        window_chunks=1)
        survivor = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, surv_tx,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=10.0)
        await decode.start()
        await doomed.start()
        task = asyncio.create_task(_drive(
            decode.generate(pre_request("rd", prompt).model_dump(
                exclude_none=True), Context("rd"))))
        # wait for two durably committed chunks, then kill the sender
        deadline = asyncio.get_event_loop().time() + 30
        while not any(s.committed_pages >= 2
                      for s in server._sessions.values()):
            assert asyncio.get_event_loop().time() < deadline, \
                "no chunk ever committed"
            await asyncio.sleep(0.02)
        await doomed.stop()
        await survivor.start()
        toks, reason = await asyncio.wait_for(task, 120)
        redelivered = plane.messaging.redeliveries
        sent_by_survivor = surv_tx.sent_pages
        await survivor.stop()
        await decode.stop()
        await server.stop()
        return toks, reason, redelivered, sent_by_survivor

    toks, reason, redelivered, sent_by_survivor = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert redelivered >= 1, "the dead sender's lease never redelivered"
    # the replacement resumed from the acked frontier: only the tail
    # crossed the wire again (5 pages total, 2 committed by the corpse)
    assert sent_by_survivor == 3
    assert XFER_STATS.resumes - r0 >= 1


def test_unrecoverable_sender_salvages_committed_prefix():
    """Link permanently dead after 3 of 5 chunks committed, resume
    budget exhausted: the decode worker SALVAGES — it keeps the
    committed pages and re-prefills locally only past the committed
    page boundary — and the stream is still token-identical."""
    prompt = list(range(50, 90))   # 5 pages; chunks 0-2 will commit
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    faults.REGISTRY.arm("transfer.link", FaultSchedule(
        0, [FaultSpec("fail_n", n=1000, skip=3)]))
    s0, r0 = XFER_STATS.salvaged_pages, XFER_STATS.resumes

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(
            plane, chunk_pages=1)
        transfer.window_chunks = 1
        transfer.link_retries = 1
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await asyncio.wait_for(_drive(
                decode.generate(pre_request("rs", prompt).model_dump(
                    exclude_none=True), Context("rs"))), 120)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()
        return (toks, reason, decode.salvaged_prefills,
                decode.full_reprefills,
                decode.majority_committed_full_reprefills)

    toks, reason, salvaged, full, majority_full = asyncio.run(main())
    assert reason == "length" and toks == expect
    assert salvaged == 1 and full == 0
    assert majority_full == 0
    # salvage charged exactly the committed pages — the local re-prefill
    # paid only for the 2 uncommitted ones
    assert XFER_STATS.salvaged_pages - s0 == 3
    assert XFER_STATS.resumes - r0 >= 1  # it did try to resume first


def test_stale_epoch_chunk_rejected_after_realloc():
    """Same request id, released and re-allocated (new epoch): a sender
    still holding the OLD allocation's epoch is fenced — its chunks
    never reach the cache — while the current-epoch sender streams
    normally."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    async def main():
        plane = MemoryPlane()
        decode = NativeEngineWorker(make_engine())
        await decode.start()
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1)
        prefill_eng = make_engine()
        s0 = XFER_STATS.stale_chunks
        try:
            alloc1 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            prefill_eng.add_request(
                EngineRequest("race", prompt, params, prefill_only=True))
            while prefill_eng.has_work():
                prefill_eng.step()
            pages = prefill_eng.extract_pages(
                prefill_eng.scheduler.parked["race"].pages)
            # release + re-allocate the SAME id: new epoch, new pages
            await decode.submit(lambda eng: eng.release_remote("race"))
            alloc2 = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("race", prompt, params)))
            assert alloc2.alloc_epoch > alloc1.alloc_epoch > 0
            with pytest.raises(RuntimeError, match="[Ss]tale"):
                await transfer.send_pages(
                    "dec-0", "race", alloc1.page_ids,
                    pages["k"], pages["v"],
                    alloc_epoch=alloc1.alloc_epoch)
            assert XFER_STATS.stale_chunks - s0 >= 1
            assert server.received_pages == 0   # nothing landed
            # the live allocation's sender is untouched by the fence
            await transfer.send_pages(
                "dec-0", "race", alloc2.page_ids,
                pages["k"], pages["v"], alloc_epoch=alloc2.alloc_epoch)
            assert server.received_pages == len(alloc2.page_ids)
        finally:
            await transfer.close()
            await server.stop()
            await decode.stop()

    asyncio.run(main())


def test_decode_restart_on_new_port_reresolves_endpoint():
    """The decode worker's transfer server restarts on a NEW port: the
    sender's pooled connection and cached endpoint are invalidated on
    the send failure and re-resolved from discovery — the next transfer
    lands on the new listener without a process restart."""
    prompt = list(range(100, 120))
    # a disjoint second prompt: a shared prefix would hit the decode
    # engine's cache after r1 and keep r2 local (no transfer to observe)
    prompt2 = list(range(130, 150))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)
    expect2 = expected(prompt2, params)

    async def main():
        plane = MemoryPlane()
        decode, prefill, server, transfer = await _build_remote_stack(plane)
        await decode.start()
        await prefill.start()
        server2 = None
        try:
            toks1, _ = await asyncio.wait_for(_drive(
                decode.generate(pre_request("r1", prompt).model_dump(
                    exclude_none=True), Context("r1"))), 60)
            old_port = server.port
            # the restart: the old listener AND its established
            # connections die (a process restart resets both), the new
            # one registers under the same engine_id on a fresh port
            await server.stop()
            server2 = await KvTransferServer(decode, "dec-0").start()
            await server2.register(plane.kv)
            assert server2.port != old_port
            toks2, _ = await asyncio.wait_for(_drive(
                decode.generate(pre_request("r2", prompt2).model_dump(
                    exclude_none=True), Context("r2"))), 60)
            return (toks1, toks2, server2.received_pages,
                    transfer._meta["dec-0"]["port"], server2.port)
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            if server2 is not None:
                await server2.stop()

    toks1, toks2, rx2, cached_port, new_port = asyncio.run(main())
    assert toks1 == expect and toks2 == expect2
    assert rx2 == 3                 # the new listener took the transfer
    assert cached_port == new_port  # endpoint re-resolved, not stale


def test_transfer_budget_exhausted_fails_fast():
    """A transfer whose request-deadline sub-budget is already spent
    must fail immediately — never block a prefill slot streaming to a
    client that has given up."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    async def main():
        plane = MemoryPlane()
        decode = NativeEngineWorker(make_engine())
        await decode.start()
        server = await KvTransferServer(decode, "dec-0").start()
        await server.register(plane.kv)
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1)
        prefill_eng = make_engine()
        try:
            alloc = await decode.submit(
                lambda eng: eng.allocate_remote(
                    EngineRequest("rb", prompt, params)))
            prefill_eng.add_request(
                EngineRequest("rb", prompt, params, prefill_only=True))
            while prefill_eng.has_work():
                prefill_eng.step()
            pages = prefill_eng.extract_pages(
                prefill_eng.scheduler.parked["rb"].pages)
            with pytest.raises(TransferBudgetExceeded):
                await asyncio.wait_for(transfer.send_pages(
                    "dec-0", "rb", alloc.page_ids, pages["k"], pages["v"],
                    budget_s=0.0), 10)
        finally:
            await transfer.close()
            await server.stop()
            await decode.stop()

    asyncio.run(main())


def test_resume_overhead_folds_into_goodput_ewma():
    """ISSUE 11 satellite: a link cut mid-stream makes the sender
    re-send its unacked chunk(s), but the TransferCostModel sample must
    count each chunk's payload ONCE over the transfer's total wall
    time — the bandwidth EWMA reflects lossy-link delivered goodput,
    never raw wire speed inflated by re-sent bytes. A scripted endpoint
    makes the re-send deterministic (receive chunk 1, cut WITHOUT
    committing it — with the real server, whether the in-flight window
    committed before the resume handshake is a race); the live-stack
    lossy path is covered by the seeded resume matrix above."""
    import numpy as np

    import msgpack

    from dynamo_tpu.disagg.remote_transfer import transfer_key
    from dynamo_tpu.observability.fleet import TRANSFER_MODEL
    from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

    observed = []
    real_observe = TRANSFER_MODEL.observe
    TRANSFER_MODEL.observe = lambda link, nbytes, seconds: observed.append(
        (link, nbytes, seconds))
    wire_chunks = []   # every chunk frame that crossed, incl. re-sends

    async def main():
        plane = MemoryPlane()
        conn_n = [0]

        async def on_connect(reader, writer):
            conn_n[0] += 1
            first = conn_n[0] == 1
            try:
                while True:
                    try:
                        frame = await read_frame(reader)
                    except (asyncio.IncompleteReadError,
                            ConnectionResetError):
                        return
                    if frame.get("op") == "resume":
                        # first stream starts fresh; the reconnect
                        # learns chunk 0 committed (chunk 1 did NOT)
                        write_frame(writer, {
                            "ok": True, "committed": 0 if first else 1})
                        await writer.drain()
                        continue
                    wire_chunks.append(frame["chunk_idx"])
                    if first and frame["chunk_idx"] >= 1:
                        # chunk 1 received but never committed/acked:
                        # cut the link — a deterministic re-send
                        writer.close()
                        return
                    write_frame(writer, {"ok": True,
                                         "chunk_idx": frame["chunk_idx"]})
                    await writer.drain()
            finally:
                writer.close()

        server = await asyncio.start_server(on_connect, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        await plane.kv.put(
            transfer_key("fake"),
            msgpack.packb({"host": "127.0.0.1", "port": port},
                          use_bin_type=True))
        transfer = RemoteTransferBackend(plane.kv, chunk_pages=1,
                                         window_chunks=1)
        z = np.zeros((2, 2, 5, 8, 4), np.float32)   # 5 pages -> 5 chunks
        await asyncio.wait_for(
            transfer.send_pages("fake", "rg", [0, 1, 2, 3, 4], z, z), 30)
        await transfer.close()
        server.close()
        await server.wait_closed()

    try:
        asyncio.run(main())
    finally:
        TRANSFER_MODEL.observe = real_observe
    # chunk 1 crossed the wire twice (cut + resume), everything else once
    assert wire_chunks == [0, 1, 1, 2, 3, 4]
    assert len(observed) == 1
    link, goodput_bytes, seconds = observed[0]
    assert link == "fake" and seconds > 0
    # the goodput sample is the UNIQUE payload: 5 equal chunks counted
    # exactly once despite 6 chunk frames on the wire
    per_chunk = goodput_bytes // 5
    assert goodput_bytes == per_chunk * 5
    assert per_chunk > 0


# -- TRUE two-process disaggregation ------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(args, env):
    return subprocess.Popen(
        [sys.executable] + args, stdout=subprocess.PIPE, cwd=REPO, env=env,
        text=True)


def _wait_ready(proc, tag, deadline=120):
    line = proc.stdout.readline()
    assert line, f"{tag} exited before READY"
    assert line.startswith("READY"), f"{tag} said {line!r}"


def test_disagg_two_processes_exact_parity():
    """Decode and prefill engines in SEPARATE OS processes; KV pages cross a
    real process boundary over the transfer plane; output matches the
    aggregated single-engine oracle exactly (VERDICT item 2 'Done' bar)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = expected(prompt, params)

    port = _free_port()
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    cp = _spawn(["-m", "dynamo_tpu.runtime.transports.server",
                 "--port", str(port)], env)
    decode = prefill = None
    try:
        # give the control-plane server a moment to bind
        deadline = 50
        for _ in range(deadline * 10):
            try:
                s = socket.create_connection(("127.0.0.1", port), 0.2)
                s.close()
                break
            except OSError:
                import time
                time.sleep(0.1)
        decode = _spawn(["tests/disagg_remote_procs.py", "decode",
                         str(port)], env)
        prefill = _spawn(["tests/disagg_remote_procs.py", "prefill",
                          str(port)], env)
        _wait_ready(decode, "decode")
        _wait_ready(prefill, "prefill")

        async def drive():
            from dynamo_tpu.runtime.distributed import DistributedRuntime
            rt = await DistributedRuntime.connect("127.0.0.1", port)
            client = rt.namespace("ns").component("decoder").endpoint(
                "generate").client()
            await client.start()
            await client.wait_for_instances()
            toks = []
            req = pre_request("two-proc", prompt).model_dump(
                exclude_none=True)
            async for frame in await client.generate(req):
                toks.extend(frame.get("token_ids", ()))
            await client.stop()
            await rt.shutdown()
            return toks

        toks = asyncio.run(asyncio.wait_for(drive(), 180))
        assert toks == expect
    finally:
        for p in (decode, prefill, cp):
            if p is not None:
                p.send_signal(signal.SIGINT)
        for p in (decode, prefill, cp):
            if p is not None:
                try:
                    p.wait(15)
                except subprocess.TimeoutExpired:
                    p.kill()
