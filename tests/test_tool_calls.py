"""Tool-call response parsing tests (reference: preprocessor/tools/response.rs)."""
import json

from dynamo_tpu.llm.tool_calls import parse_tool_calls
from dynamo_tpu.protocols.openai import ChatMessage
from dynamo_tpu.llm.tool_calls import apply_tool_calls


def test_bare_json_object():
    calls = parse_tool_calls(
        '{"name": "get_weather", "arguments": {"city": "Oslo"}}')
    assert len(calls) == 1
    c = calls[0]
    assert c["type"] == "function"
    assert c["function"]["name"] == "get_weather"
    assert json.loads(c["function"]["arguments"]) == {"city": "Oslo"}
    assert c["id"].startswith("call_")


def test_bare_json_array_and_parameters_alias():
    calls = parse_tool_calls(
        '[{"name": "a", "parameters": {"x": 1}},'
        ' {"function": {"name": "b", "arguments": "{\\"y\\": 2}"}}]')
    assert [c["function"]["name"] for c in calls] == ["a", "b"]
    assert json.loads(calls[1]["function"]["arguments"]) == {"y": 2}


def test_hermes_qwen_tags():
    text = ('<tool_call>\n{"name": "search", "arguments": {"q": "tpu"}}\n'
            '</tool_call><tool_call>{"name": "open", "arguments": {}}'
            '</tool_call>')
    calls = parse_tool_calls(text)
    assert [c["function"]["name"] for c in calls] == ["search", "open"]


def test_mistral_prefix_and_fence():
    calls = parse_tool_calls(
        '[TOOL_CALLS] [{"name": "f", "arguments": {"a": true}}]')
    assert calls[0]["function"]["name"] == "f"
    calls2 = parse_tool_calls(
        '```json\n{"name": "g", "arguments": {}}\n```')
    assert calls2[0]["function"]["name"] == "g"


def test_prose_and_malformed_rejected():
    assert parse_tool_calls("The weather in Oslo is sunny.") is None
    assert parse_tool_calls('{"no_name": true}') is None
    assert parse_tool_calls('{"name": "", "arguments": {}}') is None
    assert parse_tool_calls('{"name": "f", "arguments": "not json"}') is None
    assert parse_tool_calls('Sure! {"name": "f", "arguments": {}}') is None
    assert parse_tool_calls("") is None
    # one bad tag poisons the whole parse (no partial tool calls)
    assert parse_tool_calls(
        '<tool_call>{"name": "ok", "arguments": {}}</tool_call>'
        '<tool_call>oops</tool_call>') is None


def test_apply_tool_calls_rewrites_message():
    m = ChatMessage(role="assistant",
                    content='{"name": "f", "arguments": {"k": 1}}')
    reason = apply_tool_calls(m, "stop")
    assert reason == "tool_calls"
    assert m.content is None
    assert m.tool_calls[0]["function"]["name"] == "f"

    m2 = ChatMessage(role="assistant", content="plain prose")
    assert apply_tool_calls(m2, "stop") == "stop"
    assert m2.content == "plain prose"
    assert m2.tool_calls is None


def test_streaming_candidacy_bound():
    """ADVICE r4: candidacy must lapse for heads that can no longer parse
    as a tool call, so tools-carrying streams of ordinary code answers
    flush early instead of buffering to completion."""
    from dynamo_tpu.llm.tool_calls import could_be_tool_call_prefix as cand

    # undecided starts stay candidates
    assert cand("")
    assert cand("  ")
    assert cand("`")
    assert cand("``")
    assert cand("```")
    assert cand("```j")
    assert cand("<tool")
    assert cand("[TOOL_CA")
    # JSON-ish and json fences stay candidates
    assert cand('{"name": "f"')
    assert cand('[{"name": "f"')
    assert cand("```json")
    assert cand('```json\n{"name"')
    assert cand('```json{"name"')   # one-line fence
    assert cand('```\n{"name"')     # info-less fence wrapping JSON
    # the common code answer flushes as soon as the fence head shows it
    assert not cand("```python")
    assert not cand("```py")        # cannot grow into ```json either
    assert not cand("```python\ndef f():")
    assert not cand("```\nplain text")
    assert not cand("```jsonp")
    # prose flushes immediately
    assert not cand("Sure, here's how")
    # and even a JSON-looking head lapses past the byte bound
    long_json_prose = '{"a": "' + "x" * 100 + '"'
    assert cand(long_json_prose)
    assert not cand(long_json_prose, max_head=64)
