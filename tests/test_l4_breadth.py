"""L4/L7 breadth tests: standalone router service, build bundle, K8s
manifests (VERDICT r2 coverage rows 5/45/46; reference: components/router,
sdk cli/bentos.py + deploy.py, deploy/dynamo/operator + helm)."""
import asyncio
import json
import os

from dynamo_tpu.kv_router.main import RouterService
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheStoreData, KvCacheStoredBlockData, RouterEvent,
)
from dynamo_tpu.kv_router.publisher import KV_EVENTS_SUBJECT
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane


async def fake_worker(request, context):
    yield {"ok": True}


def test_standalone_router_service_routes_by_overlap():
    """Two workers; one publishes KV events matching the query prefix — the
    router endpoint must pick it and report overlap evidence."""
    from dynamo_tpu.engine.kv_cache import page_hash
    from dynamo_tpu.kv_router.protocols import compute_page_hashes

    async def main():
        plane = MemoryPlane()
        rts = []
        for wid in ("w0", "w1"):
            rt = await DistributedRuntime.create_local(plane, wid)
            ep = rt.namespace("ns").component("worker").endpoint("generate")
            await ep.serve(fake_worker, stats_handler=lambda: {
                "request_active_slots": 0, "request_total_slots": 4,
                "kv_active_blocks": 0, "kv_total_blocks": 16})
            rts.append(rt)
        rrt = await DistributedRuntime.create_local(plane, "router")
        svc = RouterService(rrt, "ns", "worker", block_size=4)
        await svc.start()
        try:
            tokens = list(range(1, 13))  # 3 full pages of 4
            # w1 stores the 3-page prefix: publish chained events
            comp = rts[1].namespace("ns").component("worker")
            parent = 0
            blocks = []
            for i in range(3):
                page = tokens[i * 4:(i + 1) * 4]
                h = page_hash(parent, page)
                th = compute_page_hashes(tokens, 4)[i]
                blocks.append(KvCacheStoredBlockData(h, th))
                parent = h
            ev = RouterEvent("w1", KvCacheEvent(
                1, KvCacheStoreData(parent_hash=None, blocks=blocks)))
            await comp.publish(KV_EVENTS_SUBJECT, ev.pack())
            await asyncio.sleep(0.3)  # event pump + metrics scrape

            crt = await DistributedRuntime.create_local(plane, "client")
            client = crt.namespace("ns").component("router").endpoint(
                "route").client()
            await client.start()
            await client.wait_for_instances()
            frames = [f async for f in await client.generate(
                {"token_ids": tokens})]
            assert frames[0]["worker_id"] == "w1", frames
            assert frames[0]["overlap_blocks"] == 3
            await crt.shutdown()
        finally:
            await svc.stop()
            for rt in rts + [rrt]:
                await rt.shutdown()

    asyncio.run(main())


def test_build_bundle_and_manifests(tmp_path, monkeypatch):
    from dynamo_tpu.sdk.build import (
        build_bundle, render_manifests, write_manifests,
    )

    monkeypatch.chdir(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = str(tmp_path / "bundle")
    df = build_bundle("examples.disagg.graph:Frontend", out)
    dockerfile = open(df).read()
    assert "dynamo_tpu.sdk.serve" in dockerfile
    assert os.path.exists(os.path.join(out, "dynamo_tpu", "engine",
                                       "engine.py"))
    assert os.path.exists(os.path.join(out, "graph", "examples", "disagg",
                                       "graph.py"))

    manifests = render_manifests("examples.disagg.graph:Frontend",
                                 "dynamo-tpu:test", namespace="prod")
    kinds = [(m["kind"], m["metadata"]["name"]) for m in manifests]
    assert ("Deployment", "dynamo-control-plane") in kinds
    assert ("Service", "dynamo-control-plane") in kinds
    assert ("Deployment", "dynamo-frontend") in kinds
    assert ("Service", "dynamo-frontend") in kinds
    assert ("Deployment", "dynamo-decodeworker") in kinds
    assert ("Deployment", "dynamo-prefillworker") in kinds
    for m in manifests:
        assert m["metadata"]["namespace"] == "prod"

    path = write_manifests(manifests, str(tmp_path / "k8s"))
    text = open(path).read()
    assert text.count("kind: Deployment") == 4
    assert "dynamo_tpu.sdk.run_service" in text
    # sanity: the emitted YAML must be parseable (stdlib-only check via
    # round-tripping one manifest through json-compatible structure)
    assert "containers:" in text and "replicas:" in text


def test_manifest_tpu_resources(tmp_path, monkeypatch):
    """A service declaring resources={'tpu': N} gets a TPU resource limit."""
    from dynamo_tpu.sdk.build import render_manifests
    from dynamo_tpu.sdk.service import service

    @service(name="TpuWorker", namespace="ns", component="w",
             resources={"tpu": 4}, workers=2)
    class TpuWorker:
        pass

    import sys
    mod = sys.modules[TpuWorker.__module__]
    monkeypatch.setattr(mod, "TpuWorker", TpuWorker, raising=False)
    graph = f"{TpuWorker.__module__}:TpuWorker"
    manifests = render_manifests(graph, "img")
    dep = next(m for m in manifests
               if m["metadata"]["name"] == "dynamo-tpuworker")
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    assert dep["spec"]["replicas"] == 2


def test_validate_manifests_catches_render_bugs():
    """VERDICT r3 #10: rendered YAML is schema-validated before writing."""
    import pytest

    from dynamo_tpu.sdk.build import validate_manifests

    good = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "d", "namespace": "ns"},
        "spec": {"replicas": 1,
                 "selector": {"matchLabels": {"app": "d"}},
                 "template": {
                     "metadata": {"labels": {"app": "d"}},
                     "spec": {"containers": [
                         {"name": "c", "image": "img",
                          "resources": {"limits": {"cpu": "1"}}}]}}},
    }
    validate_manifests([good])

    import copy
    broken = copy.deepcopy(good)
    broken["spec"]["selector"]["matchLabels"]["app"] = "other"
    with pytest.raises(ValueError, match="selector"):
        validate_manifests([broken])

    broken = copy.deepcopy(good)
    del broken["spec"]["template"]["spec"]["containers"][0]["image"]
    with pytest.raises(ValueError, match="name\\+image"):
        validate_manifests([broken])

    broken = copy.deepcopy(good)
    broken["spec"]["template"]["spec"]["containers"][0]["resources"] = {
        "limits": {"google.com/tpu": 4.5}}
    with pytest.raises(ValueError, match="quantity"):
        validate_manifests([broken])

    with pytest.raises(ValueError, match="missing apiVersion"):
        validate_manifests([{"kind": "Service", "metadata": {"name": "s"}}])


def test_reconcile_loop_applies_on_drift(tmp_path, monkeypatch):
    """VERDICT r3 #10 operator-lite: `deploy --watch` applies manifests,
    stays idle in sync, and re-applies on cluster drift (scale-down) —
    the reconcile role of the reference's Go operator
    (dynamodeployment_controller.go), closed with idempotent kubectl
    apply."""
    import json as _json
    import stat

    monkeypatch.chdir(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from dynamo_tpu.sdk.build import render_manifests
    from dynamo_tpu.sdk.reconcile import Reconciler

    graph = "examples.disagg.graph:Frontend"
    desired = render_manifests(graph, "img:v1")
    deployments = [m for m in desired if m["kind"] == "Deployment"]

    # stub kubectl: records invocations; `get deployments` serves a state
    # file the test mutates to simulate the cluster
    state = tmp_path / "cluster.json"
    calls = tmp_path / "calls.log"
    stub = tmp_path / "kubectl"

    def cluster_state(scale_override=None, drop=None):
        items = []
        for m in deployments:
            name = m["metadata"]["name"]
            if name == drop:
                continue
            reps = m["spec"]["replicas"]
            if scale_override and name in scale_override:
                reps = scale_override[name]
            items.append({
                "metadata": {"name": name},
                "spec": {"replicas": reps,
                         "template": m["spec"]["template"]},
                "status": {"readyReplicas": reps},
            })
        state.write_text(_json.dumps({"items": items}))

    stub.write_text(f"""#!/bin/sh
echo "$@" >> {calls}
case "$1" in
  get) cat {state} ;;
  apply) : ;;
esac
""")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)

    rec = Reconciler(graph, "img:v1", str(tmp_path / "k8s"),
                     kubectl=str(stub))
    cluster_state()
    out1 = rec.step()  # first tick: initial apply
    assert out1["applied"] and out1["reasons"] == ["initial apply"]
    out2 = rec.step()  # in sync: no apply
    assert not out2["applied"]
    assert all(s.count("/") == 1 for s in out2["status"].values())

    # drift: someone scaled a worker down by hand -> re-apply
    victim = deployments[-1]["metadata"]["name"]
    cluster_state(scale_override={victim: 0})
    out3 = rec.step()
    assert out3["applied"]
    assert any("replicas 0" in r for r in out3["reasons"])

    # drift: a Deployment was deleted -> re-apply
    cluster_state(drop=victim)
    out4 = rec.step()
    assert out4["applied"] and any("missing" in r for r in out4["reasons"])

    applies = [ln for ln in calls.read_text().splitlines()
               if ln.startswith("apply")]
    assert len(applies) == 3
