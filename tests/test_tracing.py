"""End-to-end per-request tracing (runtime/tracing.py) + serving-path
latency histograms (observability/serving.py).

Covers the ISSUE-8 acceptance contracts:
- one trace_id spans frontend -> schedule -> queue -> remote prefill ->
  KV transfer (byte counts) -> decode emits, through the REAL stack
  (HttpService + ModelWatcher + ReliableClient over the in-memory
  control plane + DisaggDecodeWorker/PrefillWorker on tiny engines);
- disabled tracing is a branch-only no-op (singleton span, empty rings);
- seeded sampling is deterministic and errors survive sampling;
- attempt spans agree with the reliability counters (migration audit);
- llm_ttft_seconds / llm_itl_seconds / llm_queue_wait_seconds render on
  the frontend /metrics with correct counts for a served request;
- tools/trace_explain.py renders a timeline from the COMMITTED disagg
  trace artifact (TRACE_DISAGG_r08.jsonl), and the chrome export loads.
"""
import asyncio
import json
import os

import pytest

from dynamo_tpu.observability.serving import SERVING
from dynamo_tpu.runtime.tracing import (
    NOOP_SPAN, TRACE_KEY, TRACER, TraceContext, chrome_trace,
)
from dynamo_tpu.runtime.engine import Context

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_TRACE = os.path.join(REPO_ROOT, "TRACE_DISAGG_r08.jsonl")


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _tracer_off_between_tests():
    """Every test starts from the production default (disabled) and
    leaves no spans behind for the next one."""
    TRACER.configure(enabled=False, sample_rate=1.0, seed=0)
    TRACER.drain()
    yield
    TRACER.configure(enabled=False, sample_rate=1.0, seed=0)
    TRACER.drain()


# -- core machinery -----------------------------------------------------------


def test_disabled_tracing_is_branch_only_noop():
    """Off (the default): no trace objects, the SAME pre-allocated span
    singleton for every call, nothing recorded anywhere."""
    assert TRACER.start_trace() is None
    t = TraceContext("tid")
    assert TRACER.span("a", t) is NOOP_SPAN
    assert TRACER.span("b", t, x=1) is NOOP_SPAN          # no allocation
    assert TRACER.begin_span("c", t) is None
    TRACER.end_span(None)                                  # no-op
    TRACER.event("d", t, n=1)
    TRACER.record_span("e", t, 0.5)
    TRACER.defer_phase("engine", "plan", 0.001)
    with TRACER.span("f", t) as sp:
        sp.set(anything=1)
        assert sp.context() is None
    assert TRACER.drain() == []


def test_span_tree_parenting_and_wire_roundtrip():
    TRACER.configure(enabled=True)
    tr = TRACER.start_trace("t-1")
    with TRACER.span("root", tr, model="m") as root:
        child_ctx = root.context()
        assert child_ctx.trace_id == "t-1"
        assert child_ctx.span_id == root.span_id
        # the wire form survives a Context hop (baggage -> rebuild)
        ctx = Context("rid", baggage={TRACE_KEY: child_ctx.to_wire()})
        assert ctx.trace is not None
        assert ctx.trace.trace_id == "t-1"
        assert ctx.trace.span_id == root.span_id
        assert ctx.child().trace.trace_id == "t-1"
        TRACER.event("leaf", ctx.trace, n=2)
    spans = {s["name"]: s for s in TRACER.drain()}
    assert spans["leaf"]["parent_id"] == spans["root"]["span_id"]
    assert spans["leaf"]["dur"] == 0.0
    assert spans["leaf"]["attrs"] == {"n": 2}
    assert spans["root"]["dur"] > 0.0


def test_seeded_sampling_deterministic_and_errors_always_captured():
    TRACER.configure(enabled=True, sample_rate=0.5, seed=11)
    first = [TRACER.sampled(f"t{i}") for i in range(200)]
    again = [TRACER.sampled(f"t{i}") for i in range(200)]
    assert first == again                       # pure fn of (seed, id)
    assert 40 < sum(first) < 160                # actually samples
    TRACER.configure(seed=12)
    assert [TRACER.sampled(f"t{i}") for i in range(200)] != first
    # errors always captured: a sampled-OUT trace records only the
    # failing span
    TRACER.configure(sample_rate=0.0, seed=11)
    tr = TRACER.start_trace("whatever")
    assert tr is not None and not tr.sampled
    with TRACER.span("quiet", tr):
        pass
    with pytest.raises(ValueError):
        with TRACER.span("boom", tr):
            raise ValueError("x")
    spans = TRACER.drain()
    assert [s["name"] for s in spans] == ["boom"]
    assert spans[0]["error"] is True


def test_ring_buffer_bounded_and_drop_counted():
    TRACER.configure(enabled=True, sample_rate=1.0)
    # a fresh tracer so the capacity applies to a new ring
    from dynamo_tpu.runtime.tracing import Tracer
    t = Tracer().configure(enabled=True, sample_rate=1.0, ring_capacity=8)
    tr = t.start_trace("ring")
    for i in range(20):
        t.event(f"e{i}", tr)
    spans = t.drain()
    assert len(spans) == 8
    assert [s["name"] for s in spans] == [f"e{i}" for i in range(12, 20)]
    assert t.dropped() == 12


def test_span_ids_carry_process_prefix_and_merged_files_explain():
    """Span ids embed a per-process prefix (merging span files from the
    frontend/decode/prefill processes must not collide ids), and
    trace_explain survives a malformed file where ids DO collide (the
    pre-fix shape: counter-only ids from two processes forming a parent
    cycle) instead of recursing forever."""
    TRACER.configure(enabled=True)
    tr = TRACER.start_trace("pfx")
    with TRACER.span("a", tr):
        pass
    span, = TRACER.drain()
    from dynamo_tpu.runtime.tracing import _ID_PREFIX
    assert span["span_id"].startswith(_ID_PREFIX + "-")

    from tools.trace_explain import explain
    base = {"ts": 0.0, "dur": 0.001, "attrs": None, "error": False,
            "thread": "t"}
    cyclic = [  # two processes both minted "s1"/"s2"; links form a loop
        {**base, "trace_id": "t", "span_id": "s1", "parent_id": "s2",
         "name": "worker.generate"},
        {**base, "trace_id": "t", "span_id": "s2", "parent_id": "s1",
         "name": "attempt"},
        {**base, "trace_id": "t", "span_id": "s1", "parent_id": "",
         "name": "http.request"},
    ]
    text = explain(cyclic, "t")          # must terminate
    assert "worker.generate" in text and "attempt" in text


def test_chrome_trace_loadable_shape():
    TRACER.configure(enabled=True)
    tr = TRACER.start_trace("ct")
    with TRACER.span("outer", tr, k="v"):
        TRACER.event("instant", tr)
    ct = chrome_trace(TRACER.drain())
    blob = json.loads(json.dumps(ct))           # JSON-serializable
    evs = blob["traceEvents"]
    assert len(evs) == 2
    by_name = {e["name"]: e for e in evs}
    assert by_name["outer"]["ph"] == "X" and by_name["outer"]["dur"] > 0
    assert by_name["instant"]["ph"] == "i"
    assert all(e["ts"] >= 0 for e in evs)
    assert by_name["outer"]["args"]["trace_id"] == "ct"


# -- the full-stack disagg trace (the acceptance span tree) -------------------

# every leg the ISSUE-8 criterion names, in ONE trace
REQUIRED_LEGS = {"http.request", "schedule", "attempt", "prefill.remote",
                 "queue.wait", "prefill.run", "kv.transfer", "decode.emit"}


async def _serve_disagg_request():
    """HTTP frontend -> ReliableClient over the wire -> DisaggDecodeWorker
    (remote prefill via the leased queue + LocalTransferBackend) -> SSE
    stream back. Returns (status, drained spans)."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.frontend.discovery import ModelWatcher, register_model
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    from tests.http_client import request

    cfg = ModelConfig(dtype="float32", max_model_len=512)

    def make_engine():
        return NativeEngine(cfg, EngineConfig(
            page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
            prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)

    card = ModelDeploymentCard(name="tiny", arch="tiny",
                               tokenizer_kind="byte", context_length=512,
                               eos_token_ids=[2])
    plane = MemoryPlane()
    wrt = await DistributedRuntime.create_local(plane, "dec-0")
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=4,
                                 max_prefill_queue_size=4, model="tiny")
    decode = DisaggDecodeWorker(make_engine(), plane.messaging, router,
                                queue, worker_id="dec-0",
                                prefill_timeout_s=30.0)
    transfer = LocalTransferBackend()
    transfer.register("dec-0", decode)
    prefill = PrefillWorker(NativeEngineWorker(make_engine()), queue,
                            transfer, plane.messaging)
    await decode.start()
    await prefill.start()
    await serve_llm_worker(wrt, "ns", "backend", decode, card=card)

    frt = await DistributedRuntime.create_local(plane, "front")
    svc = await HttpService("127.0.0.1", 0).start()
    watcher = await ModelWatcher(frt, svc.models).start()
    await register_model(frt.kv, "tiny", "ns", "backend", card,
                         model_type="chat")
    for _ in range(100):
        if "tiny" in svc.models.chat:
            break
        await asyncio.sleep(0.02)
    try:
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
            {"model": "tiny", "max_tokens": 6, "messages": [
                {"role": "user", "content": "trace this slow request"}]})
        assert decode.remote_prefills == 1, "remote prefill path not taken"
    finally:
        await watcher.stop()
        await svc.stop()
        await prefill.stop()
        await decode.stop()
        await frt.shutdown()
        await wrt.shutdown()
    return status, TRACER.drain()


def test_disagg_request_yields_single_trace_span_tree(tmp_path):
    """One trace_id covers frontend ingest, schedule, leased-queue wait,
    remote prefill, KV transfer (with byte counts) and decode emits; the
    exported JSONL + chrome trace round-trip through trace_explain."""
    TRACER.configure(enabled=True, sample_rate=1.0)
    TRACER.drain()
    status, spans = run(_serve_disagg_request())
    assert status == 200

    request_traces = {}
    for s in spans:
        if not s["trace_id"].startswith("scope:"):
            request_traces.setdefault(s["trace_id"], []).append(s)
    # exactly one request flowed -> exactly one request trace
    assert len(request_traces) == 1, sorted(request_traces)
    (tid, mine), = request_traces.items()
    names = {s["name"] for s in mine}
    assert REQUIRED_LEGS <= names, REQUIRED_LEGS - names

    # the transfer leg carries byte counts
    xfer = [s for s in mine if s["name"] == "kv.transfer"]
    assert xfer and all(s["attrs"]["bytes"] > 0 for s in xfer)
    assert all(s["attrs"]["pages"] > 0 for s in xfer)
    # decode emits: first token + streamed windows, all under this trace
    emits = [s for s in mine if s["name"] == "decode.emit"]
    assert len(emits) >= 2
    # parenting: the attempt hangs off the http root, the remote prefill
    # under the worker side of that attempt
    by_id = {s["span_id"]: s for s in mine}
    root = next(s for s in mine if s["name"] == "http.request")
    attempt = next(s for s in mine if s["name"] == "attempt")
    assert attempt["parent_id"] == root["span_id"]
    remote = next(s for s in mine if s["name"] == "prefill.remote")
    assert remote["parent_id"] in by_id
    # engine phase spans rode the deferred recorder under scope:engine
    assert any(s["trace_id"] == "scope:engine" for s in spans)

    # export: JSONL via tools/artifacts + chrome trace, then explain
    from tools.artifacts import append_jsonl, write_json
    out = os.environ.get("DYN_TRACE_ARTIFACT",
                         str(tmp_path / "trace_disagg.jsonl"))
    for s in spans:
        append_jsonl(out, s)
    write_json(out + ".chrome.json", chrome_trace(spans), overwrite=True)
    assert json.load(open(out + ".chrome.json"))["traceEvents"]

    from tools.trace_explain import explain, load_spans, pick_trace
    loaded = load_spans(out)
    assert pick_trace(loaded) == tid
    text = explain(loaded, tid)
    for needle in ("http.request", "kv transfer", "queue wait",
                   "decode:", "attempts:"):
        assert needle in text, (needle, text)


def test_trace_explain_renders_committed_artifact():
    """The committed disagg capture stays explainable: timeline + every
    latency-attribution leg from TRACE_DISAGG_r08.jsonl (generated by
    the e2e test above with DYN_TRACE_ARTIFACT, committed per the
    tools/artifacts.py evidence policy)."""
    from tools.trace_explain import explain, load_spans, pick_trace
    spans = load_spans(COMMITTED_TRACE)
    assert spans, f"missing committed artifact {COMMITTED_TRACE}"
    tid = pick_trace(spans)
    names = {s["name"] for s in spans if s["trace_id"] == tid}
    assert REQUIRED_LEGS <= names, REQUIRED_LEGS - names
    text = explain(spans, tid)
    assert "kv transfer" in text and "bytes" in text
    assert "queue wait" in text
    assert "decode:" in text
    assert "attempts: 1 (success×1)" in text


# -- attempt linking audit (reliability counters vs the trace) ----------------


def test_attempt_spans_agree_with_reliability_counters():
    """Migration clones ({id}~a{n}) carry the parent trace, and the
    per-terminal-status attempt spans agree with the counters."""
    from dynamo_tpu.frontend.reliability import (
        CircuitBreaker, ReliabilityMetrics, ReliabilityPolicy,
        ReliableClient,
    )
    from tests.test_reliability import _serving_pair, pre_request
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    TRACER.configure(enabled=True, sample_rate=1.0)
    TRACER.drain()

    async def main():
        rts, client = await _serving_pair(MemoryPlane())
        metrics = ReliabilityMetrics()
        rel = ReliableClient(
            client,
            ReliabilityPolicy(stall_timeout_s=0.2, max_attempts=6,
                              backoff_base_s=0.01),
            breaker=CircuitBreaker(failure_threshold=1, cooldown_s=30.0,
                                   metrics=metrics),
            metrics=metrics)
        prompt = list(range(10, 22))
        try:
            for i in range(4):
                tr = TRACER.start_trace(f"audit-{i}")
                ctx = Context(f"m{i}", baggage={TRACE_KEY: tr.to_wire()})
                toks = []
                async for frame in rel.generate(
                        pre_request(f"m{i}", prompt, 12), ctx):
                    toks.extend(frame.get("token_ids", ()))
                assert toks == prompt
        finally:
            for rt in rts:
                await rt.shutdown()
        return metrics.snapshot()

    snap = run(main())
    spans = TRACER.drain()
    attempts = [s for s in spans if s["name"] == "attempt"]
    outcomes = {}
    for s in attempts:
        outcomes.setdefault(s["attrs"]["outcome"], []).append(s)
    # audit: what the counters claim is what the trace shows
    assert len(outcomes.get("migrated", ())) == snap["migrations"] >= 1
    assert len(outcomes.get("retried", ())) == snap["retries"]
    assert len(outcomes.get("success", ())) == 4       # one per request
    # migration attempts carry the PARENT trace and the clone id
    migrated = outcomes["migrated"][0]
    follow_up = [s for s in attempts
                 if s["trace_id"] == migrated["trace_id"]
                 and s["attrs"]["attempt"] > migrated["attrs"]["attempt"]]
    assert follow_up, "migrated attempt has no successor in its trace"
    assert any("~a" in s["attrs"]["engine_request_id"] for s in follow_up)
    assert all(s["attrs"]["resumed_tokens"] > 0 for s in follow_up)
    # worker-side spans landed under the same traces (cross-wire link)
    worker_spans = [s for s in spans if s["name"] == "worker.generate"]
    assert worker_spans
    assert {s["trace_id"] for s in worker_spans} <= \
        {s["trace_id"] for s in attempts}


# -- serving histograms on /metrics -------------------------------------------


def test_frontend_metrics_serve_ttft_and_itl_histograms():
    """llm_ttft_seconds / llm_itl_seconds / llm_queue_wait_seconds appear
    on the frontend /metrics with correct counts for a served request
    (echo engine: one frame per token, single choice)."""
    from dynamo_tpu.frontend.reliability import AdmissionControl
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.pipeline import LocalPipeline
    from dynamo_tpu.llm.worker import EchoTokenEngine

    from tests.http_client import request

    SERVING.reset()

    async def main():
        card = ModelDeploymentCard(name="echo-model", arch="tiny",
                                   tokenizer_kind="byte",
                                   context_length=512, eos_token_ids=[2])
        pipe = LocalPipeline(card, EchoTokenEngine())
        svc = await HttpService(
            "127.0.0.1", 0,
            admission=AdmissionControl(max_inflight=8)).start()
        svc.models.add("echo-model", pipe, "chat")
        status, body = await request(
            "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
            {"model": "echo-model", "max_tokens": 500,
             "messages": [{"role": "user", "content": "hello tpu"}]})
        assert status == 200
        usage = json.loads(body)["usage"]
        mstatus, mbody = await request("127.0.0.1", svc.port, "GET",
                                       "/metrics")
        await svc.stop()
        return usage, mstatus, mbody.decode()

    usage, mstatus, text = run(main())
    assert mstatus == 200
    n_tokens = usage["completion_tokens"]
    assert n_tokens > 1
    # exactly one first-token observation, one ITL per later frame —
    # series carry the request's QoS class label (runtime/qos.py;
    # unclassed requests label as the policy default "standard")
    assert ('llm_ttft_seconds_count{model="echo-model",qos="standard"} 1'
            in text)
    assert ('llm_itl_seconds_count{model="echo-model",qos="standard"} '
            f"{n_tokens - 1}") in text
    assert ('llm_ttft_seconds_bucket{model="echo-model",qos="standard",'
            'le="+Inf"} 1') in text
    assert 'llm_queue_wait_seconds_count{qos="standard"} 1' in text
    assert "# TYPE llm_ttft_seconds histogram" in text
    assert "# TYPE llm_schedule_seconds histogram" in text


def test_exporter_folds_serving_histograms():
    """The standalone exporter's /metrics appends the same serving
    histograms (render-time fold)."""
    SERVING.reset()
    SERVING.ttft.observe("m", "standard", value=0.02)
    SERVING.kv_transfer.observe(value=0.003)
    from dynamo_tpu.observability.exporter import MetricsExporter
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    from tests.http_client import request

    async def main():
        plane = MemoryPlane()
        rt = await DistributedRuntime.create_local(plane, "exp")
        exp = await MetricsExporter(rt, "ns", "backend").start()
        status, body = await request("127.0.0.1", exp.port, "GET",
                                     "/metrics")
        await exp.stop()
        await rt.shutdown()
        return status, body.decode()

    status, text = run(main())
    assert status == 200
    assert 'llm_ttft_seconds_count{model="m",qos="standard"} 1' in text
    assert "llm_kv_transfer_seconds_count 1" in text


# -- tool plumbing ------------------------------------------------------------


def test_chaos_replay_trace_flag_writes_artifacts(tmp_path, monkeypatch):
    """--trace captures spans around a scenario run and writes the JSONL
    + chrome twin through tools/artifacts.py."""
    import tools.chaos_replay as cr

    class _StubChaos:
        SCENARIOS = {name: (None, {"site": {"seed": 1, "specs": []}})
                     for name in cr.SCENARIO_NAMES}

        @staticmethod
        def run_scenario(name, plan):
            tr = TRACER.start_trace("chaos-span")
            with TRACER.span("storm", tr, scenario=name):
                pass
            return {"ok": 1}

    monkeypatch.setattr(cr, "_load_scenarios", lambda: _StubChaos)
    out = str(tmp_path / "chaos_trace.jsonl")
    rc = cr.main(["rolling_restart", "--trace", out])
    assert rc == 0
    lines = [json.loads(x) for x in open(out) if x.strip()]
    assert any(s["name"] == "storm" for s in lines)
    chrome = json.load(open(out + ".chrome.json"))
    assert chrome["traceEvents"]
    assert TRACER.enabled  # --trace armed the tracer for the run
