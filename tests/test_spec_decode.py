"""Speculative decoding (engine/spec.py): exactness, acceptance, fallbacks.

The invariant under test everywhere: speculative greedy output is
token-for-token identical to plain greedy output — drafts only ever change
speed, never content.
"""
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.engine.spec import ngram_propose

CFG = ModelConfig(dtype="float32", max_model_len=512)


def make_engine(**kw):
    defaults = dict(
        page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512)
    defaults.update(kw)
    return NativeEngine(CFG, EngineConfig(**defaults), seed=0)


# -- proposer ------------------------------------------------------------------

def test_ngram_propose_finds_continuation():
    toks = [1, 2, 3, 4, 9, 9, 1, 2, 3]
    # suffix 3-gram [1,2,3] matched at position 0 -> continuation [4, 9, 9]
    assert ngram_propose(toks, k=3) == [4, 9, 9]
    assert ngram_propose(toks, k=2) == [4, 9]


def test_ngram_propose_prefers_most_recent_match():
    toks = [1, 2, 5, 7, 1, 2, 6, 8, 1, 2]
    # both occurrences of [1,2] qualify; the later one (-> 6) wins
    assert ngram_propose(toks, k=1, max_ngram=2) == [6]


def test_ngram_propose_overlapping_run():
    # a trailing repeat proposes more of itself (overlap allowed); a
    # shorter-n full-length draft beats an end-truncated longer match
    assert ngram_propose([7, 7, 7, 7], k=2, min_ngram=2) == [7, 7]
    assert ngram_propose([7, 7, 7, 7, 7], k=2, min_ngram=2) == [7, 7]


def test_ngram_propose_no_match_or_short():
    assert ngram_propose([1, 2, 3, 4, 5], k=4) == []
    assert ngram_propose([1, 2], k=4) == []
    assert ngram_propose([1, 2, 3], k=0) == []


# -- exactness vs plain greedy -------------------------------------------------

def repetitive_prompt():
    """A prompt with internal repetition so prompt-lookup fires."""
    phrase = [11, 12, 13, 14, 15, 16]
    return phrase * 4 + [20, 21] + phrase * 2


@pytest.mark.parametrize("prompt", [
    repetitive_prompt(),
    list(range(10, 40)),          # no repetition: near-zero acceptance
    [5, 6, 5, 6, 5, 6, 5, 6],     # overlapping short-period repeats
])
def test_spec_exact_vs_plain(prompt):
    p = SamplingParams(max_tokens=12, temperature=0.0)
    plain = make_engine().generate(prompt, p, "plain")
    spec = make_engine(spec_decode="ngram", spec_k=4)
    out = spec.generate(prompt, p, "spec")
    assert out == plain


def test_spec_exact_concurrent_batch():
    """Mixed concurrent requests (some lookup-friendly, some not) must each
    match their solo plain-greedy output."""
    prompts = [repetitive_prompt(), list(range(40, 60)),
               [3, 4, 5] * 6]
    p = SamplingParams(max_tokens=7, temperature=0.0)
    solo = [make_engine().generate(pr, p, f"s{i}")
            for i, pr in enumerate(prompts)]
    eng = make_engine(spec_decode="ngram", spec_k=4)
    for i, pr in enumerate(prompts):
        eng.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(len(prompts))}
    done = set()
    while len(done) < len(prompts):
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
    assert [got[f"r{i}"] for i in range(len(prompts))] == solo


def test_spec_exact_min_tokens_and_stops(monkeypatch):
    """min_tokens eos ban and hidden stop ids must behave identically under
    speculation (the verify program replays the eos ban per position).

    A random-weight model's generated tokens never repeat, so the real
    n-gram proposer goes silent after the first token and the window path
    would trivially pass — an oracle draft source (fed the plain engine's
    own output) forces every stop/ban interaction through the VERIFY
    commit path instead."""
    prompt = repetitive_prompt()
    p0 = SamplingParams(max_tokens=10, temperature=0.0)
    plain = make_engine().generate(prompt, p0, "probe")

    import dynamo_tpu.engine.spec as spec_mod
    oracle_seq: list = []

    def oracle_propose(tokens, k, min_ngram=2, max_ngram=4, max_scan=4096,
                       vocab_size=None):
        done = len(tokens) - len(prompt)
        return oracle_seq[done:done + k]

    monkeypatch.setattr(spec_mod, "ngram_propose", oracle_propose)

    def eng(eos=None, **kw):
        defaults = dict(page_size=8, num_pages=64, max_slots=4,
                        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                        max_model_len=512)
        defaults.update(kw)
        from dynamo_tpu.engine.engine import NativeEngine
        return NativeEngine(CFG, EngineConfig(**defaults), seed=0,
                            eos_token_ids=eos)

    # hidden-stop leg: stop on a token the plain run actually emits
    stop_tok = plain[len(plain) // 2]
    params = SamplingParams(max_tokens=10, temperature=0.0,
                            stop_token_ids=(stop_tok,))
    a = eng().generate(prompt, params, "a")
    oracle_seq[:] = a
    spec = eng(spec_decode="ngram", spec_k=4)
    b = spec.generate(prompt, params, "b")
    assert b == a
    assert spec.spec_steps > 0  # the verify path actually ran

    # eos-ban leg: a REAL eos id the greedy run hits early, so the
    # min-tokens ban changes the continuation and the verify program's
    # per-position replay of the ban is what keeps outputs identical
    eos_tok = plain[2]
    params = SamplingParams(max_tokens=10, temperature=0.0, min_tokens=5)
    a = eng(eos={eos_tok}).generate(prompt, params, "a2")
    assert len(a) >= 5  # the ban actually kept the request alive
    oracle_seq[:] = a
    spec = eng(eos={eos_tok}, spec_decode="ngram", spec_k=4)
    b = spec.generate(prompt, params, "b2")
    assert b == a
    assert spec.spec_steps > 0


def test_spec_max_tokens_edges():
    prompt = repetitive_prompt()
    for mt in (1, 2, 3):
        p = SamplingParams(max_tokens=mt, temperature=0.0)
        a = make_engine().generate(prompt, p, "a")
        b = make_engine(spec_decode="ngram",
                        spec_k=4).generate(prompt, p, "b")
        assert b == a
        assert len(b) == mt


# -- acceptance actually saves steps -------------------------------------------

def test_spec_oracle_draft_accepts_fully(monkeypatch):
    """With a draft source that proposes the true greedy continuation, every
    draft is accepted: the spec engine finishes in far fewer device steps
    and still emits the identical tokens. Proves the verify/accept path
    does real multi-token progress, not one-token fallback."""
    prompt = list(range(10, 30))
    p = SamplingParams(max_tokens=12, temperature=0.0)
    plain = make_engine().generate(prompt, p, "oracle")

    def oracle_propose(tokens, k, min_ngram=2, max_ngram=4, max_scan=4096,
                       vocab_size=None):
        done = len(tokens) - len(prompt)
        return plain[done:done + k]

    import dynamo_tpu.engine.spec as spec_mod
    monkeypatch.setattr(spec_mod, "ngram_propose", oracle_propose)
    spec = make_engine(spec_decode="ngram", spec_k=4)
    steps_before = spec.step_count
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    decode_steps = spec.step_count - steps_before - 1  # minus the prefill
    # 12 tokens at <=5/step (4 drafts + bonus) needs >=3 decode dispatches;
    # plain needs 12 single-token steps (window path would compress too,
    # but the oracle asserts the SPEC path compresses)
    assert decode_steps <= 5
    assert spec.spec_accepted_tokens == spec.spec_proposed_tokens > 0
    m = spec.metrics()
    assert m.spec_accepted_tokens == spec.spec_accepted_tokens
    assert m.spec_proposed_tokens == spec.spec_proposed_tokens


def test_spec_wrong_drafts_all_rejected(monkeypatch):
    """A maximally wrong draft source costs steps but never corrupts
    output."""
    prompt = list(range(10, 30))
    p = SamplingParams(max_tokens=6, temperature=0.0)
    plain = make_engine().generate(prompt, p, "plain")

    import dynamo_tpu.engine.spec as spec_mod

    def wrong_propose(tokens, k, min_ngram=2, max_ngram=4, max_scan=4096,
                       vocab_size=None):
        return [(tokens[-1] + 1) % 100] * k

    monkeypatch.setattr(spec_mod, "ngram_propose", wrong_propose)
    spec = make_engine(spec_decode="ngram", spec_k=4)
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    assert spec.spec_proposed_tokens > 0
    assert spec.spec_accepted_tokens == 0


# -- fallbacks -----------------------------------------------------------------

def test_spec_sampled_plan_falls_back_to_window():
    """Sampled plans bypass the verify path entirely and match the plain
    engine's sampled output at a fixed seed."""
    prompt = repetitive_prompt()
    p = SamplingParams(max_tokens=8, temperature=0.8, top_k=20, seed=7)
    a = make_engine().generate(prompt, p, "a")
    spec = make_engine(spec_decode="ngram", spec_k=4)
    b = spec.generate(prompt, p, "b")
    assert b == a
    assert spec.spec_steps == 0


def test_spec_gate_returns_to_window_on_rejection(monkeypatch):
    """With consistently rejected drafts the acceptance EMA collapses and
    the cost gate hands the batch back to the fused window (one lucky
    n-gram hit must not trade an nw-step window for one-shot verifies
    forever — code-review r5). A forced probe still refreshes the EMA."""
    prompt = list(range(10, 30))
    p = SamplingParams(max_tokens=24, temperature=0.0)
    plain = make_engine(decode_steps=8).generate(prompt, p, "plain")

    import dynamo_tpu.engine.spec as spec_mod

    def wrong_propose(tokens, k, min_ngram=2, max_ngram=4, max_scan=4096,
                       vocab_size=None):
        return [(tokens[-1] + 1) % 100] * k

    monkeypatch.setattr(spec_mod, "ngram_propose", wrong_propose)
    spec = make_engine(decode_steps=8, spec_decode="ngram", spec_k=4,
                       spec_probe_every=1000)
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    # EMA decays 0.8^n from 1.0; the nw=8, r=2 gate needs
    # (1 + ema*4)*10 > 24 i.e. ema > 0.35 -> ~5 big-window verify
    # dispatches before the window takes over. Small tail rungs (nw<=2,
    # where a verify is a strict superset of a single step) legitimately
    # re-pass the gate, so allow a few more — but a pure-spec run would
    # take 24 (one per token): well below that proves the gate engaged.
    assert 1 <= spec.spec_steps <= 9
    assert spec._spec_acc_ema < 0.35
    # the probe path deterministically re-enables a verify on the Nth
    # consecutive gate rejection (end-to-end step counts are fragile:
    # tail rungs where verify is a superset re-pass the gate on their own)
    import types
    eng = make_engine(decode_steps=8, spec_decode="ngram", spec_k=4,
                      spec_probe_every=3)
    eng._spec_acc_ema = 0.0  # collapsed: big-window gate always rejects
    plan8 = types.SimpleNamespace(seqs=[object()], n_window=8)
    assert not eng._spec_worthwhile(plan8, 4)   # skip 1
    assert not eng._spec_worthwhile(plan8, 4)   # skip 2
    assert eng._spec_worthwhile(plan8, 4)       # skip 3 -> forced probe
    assert not eng._spec_worthwhile(plan8, 4)   # counter reset
    # the bound precheck rejects without paying the n-gram scan, but
    # still advances the probe cadence and lets the probe through
    eng2 = make_engine(decode_steps=8, spec_decode="ngram", spec_k=4,
                       spec_probe_every=3)
    eng2._spec_acc_ema = 0.0
    assert not eng2._spec_bound_ok(plan8)       # skip 1, scan avoided
    assert not eng2._spec_bound_ok(plan8)       # skip 2
    assert eng2._spec_bound_ok(plan8)           # probe due -> scan allowed
    # with a healthy EMA the bound passes outright and no skip is counted
    eng2._spec_acc_ema = 1.0
    eng2._spec_gate_skips = 0
    assert eng2._spec_bound_ok(plan8)
    assert eng2._spec_gate_skips == 0


def test_spec_empty_probe_resets_cadence(monkeypatch):
    """A probe-granted scan that finds no drafts must spend the probe —
    otherwise the skip counter sticks at the threshold and the precheck
    admits the (pointless) n-gram scan on every step forever
    (code-review r5)."""
    import dynamo_tpu.engine.spec as spec_mod
    monkeypatch.setattr(spec_mod, "ngram_propose",
                        lambda *a, **k: [])
    eng = make_engine(decode_steps=8, spec_decode="ngram", spec_k=4,
                      spec_probe_every=4)
    eng._spec_acc_ema = 0.0        # bound precheck rejects every step
    eng._spec_gate_skips = 4       # probe due on the first decode step
    p = SamplingParams(max_tokens=12, temperature=0.0)
    eng.generate(list(range(10, 30)), p, "r")
    assert eng.spec_steps == 0                 # nothing ever verified
    assert eng._spec_gate_skips < 4            # cadence was reset


def test_spec_config_validation():
    with pytest.raises(ValueError, match="spec_decode"):
        make_engine(spec_decode="eagle")
    with pytest.raises(ValueError, match="spec_k"):
        make_engine(spec_decode="ngram", spec_k=0)
    # sp routes any Tq>1 forward to ring attention (chunk-internal only),
    # which would silently drop the verify block's KV prefix — the engine
    # must refuse the combination even on a VALID sp mesh
    from dynamo_tpu.parallel.mesh import make_mesh
    from dynamo_tpu.engine.engine import NativeEngine
    with pytest.raises(ValueError, match="ring-attention"):
        NativeEngine(
            CFG,
            EngineConfig(page_size=8, num_pages=64, max_slots=4,
                         max_prefill_chunk=512,
                         prefill_buckets=(8, 16, 32), max_model_len=512,
                         sp=2, spec_decode="ngram"),
            mesh=make_mesh(sp=2), seed=0)


# -- draft-model mode ----------------------------------------------------------

@pytest.fixture
def f32_draft():
    """Registry entry matching the test CFG exactly (the registry 'tiny'
    is bf16; an identical-draft test needs identical arithmetic)."""
    import dynamo_tpu.engine.config as cfg_mod
    cfg_mod._CONFIGS["tiny-f32-test"] = CFG
    yield "tiny-f32-test"
    cfg_mod._CONFIGS.pop("tiny-f32-test", None)


def test_spec_draft_same_model_accepts_fully(f32_draft):
    """A draft IDENTICAL to the target (same registry config, same seed)
    proposes exactly the target's greedy continuation, so on CPU/f32
    every draft is accepted: far fewer dispatches, identical tokens, and
    acceptance == 1.0. The strongest end-to-end proof that the draft's
    page-table-sharing KV cache and catch-up replay are correct."""
    prompt = list(range(10, 30))
    p = SamplingParams(max_tokens=16, temperature=0.0)
    plain = make_engine().generate(prompt, p, "plain")
    spec = make_engine(spec_decode="draft", spec_draft_model=f32_draft,
                       spec_k=4)
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    assert spec.spec_steps > 0
    assert spec.spec_accepted_tokens == spec.spec_proposed_tokens > 0
    # 16 tokens at 5/dispatch (4 accepted + bonus) + prefill
    assert spec.step_count <= 1 + 5


def test_spec_draft_divergent_model_still_exact(f32_draft):
    """A draft with DIFFERENT weights (different seed) proposes garbage;
    acceptance collapses but output remains token-for-token the plain
    greedy output — including across gate-driven window interludes,
    which exercise the catch-up replay path."""
    prompt = repetitive_prompt()
    p = SamplingParams(max_tokens=20, temperature=0.0)
    plain = make_engine(decode_steps=8).generate(prompt, p, "plain")
    spec = make_engine(decode_steps=8, spec_decode="draft",
                       spec_draft_model=f32_draft, spec_k=4,
                       spec_probe_every=2)
    # different draft weights: seed the DRAFT differently by replacing
    # its params after build (same arch, fresh init)
    import jax

    from dynamo_tpu.models import llama
    spec._draft.params = jax.device_put(
        llama.init_params(jax.random.PRNGKey(123), cfg=spec._draft.cfg))
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    assert spec.spec_steps > 0
    # garbage drafts: acceptance must be far below full
    assert spec.spec_accepted_tokens < spec.spec_proposed_tokens


def test_spec_draft_concurrent_batch_exact(f32_draft):
    """Concurrent requests through the draft path must each match their
    solo plain output (the shared draft cache must not cross-pollute
    slots)."""
    prompts = [list(range(3, 19)), list(range(40, 56)),
               list(range(7, 23))]
    p = SamplingParams(max_tokens=9, temperature=0.0)
    solo = [make_engine().generate(pr, p, f"s{i}")
            for i, pr in enumerate(prompts)]
    eng = make_engine(spec_decode="draft", spec_draft_model=f32_draft,
                      spec_k=4)
    for i, pr in enumerate(prompts):
        eng.add_request(EngineRequest(f"r{i}", pr, p))
    got = {f"r{i}": [] for i in range(len(prompts))}
    done = set()
    while len(done) < len(prompts):
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
    assert [got[f"r{i}"] for i in range(len(prompts))] == solo
    assert eng.spec_accepted_tokens == eng.spec_proposed_tokens > 0


def test_spec_draft_pos_pruned_on_finish(f32_draft):
    """Requests that finish INSIDE a verify step (the common path: the
    max_tokens budget lands mid-block) must not leave draft coverage
    entries behind — a leak, and a coverage-poisoning hazard if a client
    reuses a request id (code-review r5)."""
    eng = make_engine(spec_decode="draft", spec_draft_model=f32_draft,
                      spec_k=4)
    p = SamplingParams(max_tokens=6, temperature=0.0)
    eng.generate(list(range(10, 26)), p, "r1")
    eng.generate(list(range(30, 46)), p, "r2")
    assert eng.spec_steps > 0
    assert eng._draft.pos == {}


def test_spec_draft_config_validation():
    with pytest.raises(ValueError, match="spec_draft_model"):
        make_engine(spec_decode="draft")
    # vocab mismatch refused up front (draft ids feed the target verify)
    import dataclasses

    from dynamo_tpu.engine.config import _CONFIGS
    small_vocab = dataclasses.replace(_CONFIGS["tiny"],
                                      vocab_size=64)
    import dynamo_tpu.engine.config as cfg_mod
    cfg_mod._CONFIGS["tiny-smallvocab"] = small_vocab
    try:
        with pytest.raises(ValueError, match="vocab"):
            make_engine(spec_decode="draft",
                        spec_draft_model="tiny-smallvocab")
    finally:
        cfg_mod._CONFIGS.pop("tiny-smallvocab", None)


def test_spec_draft_disagg_decode_side(f32_draft):
    """Disaggregated serving with a draft-speculating DECODE engine: the
    remotely-prefilled prompt's KV never went through the draft, so the
    first spec step's catch-up replays the whole prompt before proposing
    (the docstring's 'disagg activation' claim, tested). Tokens must
    match the aggregated oracle and — identical draft, f32 — every
    post-catch-up draft must be accepted."""
    import asyncio

    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    decode_engine = make_engine(spec_decode="draft",
                                spec_draft_model=f32_draft, spec_k=4)

    async def main():
        plane = MemoryPlane()
        transfer = LocalTransferBackend()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=4,
                                     model="tiny")
        decode = DisaggDecodeWorker(decode_engine, plane.messaging, router,
                                    queue, worker_id="dec-0",
                                    prefill_timeout_s=30.0)
        transfer.register("dec-0", decode)
        prefill = PrefillWorker(NativeEngineWorker(make_engine()), queue,
                                transfer, plane.messaging)
        await decode.start()
        await prefill.start()
        try:
            req = PreprocessedRequest(
                request_id="r1", token_ids=prompt,
                stop=StopConditions(max_tokens=6, ignore_eos=True))
            toks = []
            async for frame in decode.generate(
                    req.model_dump(exclude_none=True), Context("r1")):
                toks.extend(frame.get("token_ids", ()))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, decode.remote_prefills

    toks, n_remote = asyncio.run(main())
    assert n_remote == 1
    assert toks == expect
    assert decode_engine.spec_steps > 0
    assert (decode_engine.spec_accepted_tokens
            == decode_engine.spec_proposed_tokens > 0)


def test_spec_composes_with_int8_target(f32_draft):
    """Weight-only int8 serving + speculative decoding: the verify block
    and the window path both read the same quantized weights through
    wmat, so spec output must match the plain int8 engine exactly (the
    draft stays full precision)."""
    import dataclasses

    qcfg = dataclasses.replace(CFG, quant="int8")
    prompt = repetitive_prompt()
    p = SamplingParams(max_tokens=10, temperature=0.0)
    kw = dict(page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
              prefill_buckets=(8, 16, 32), max_model_len=512)
    plain = NativeEngine(qcfg, EngineConfig(**kw), seed=0).generate(
        prompt, p, "plain")
    spec = NativeEngine(qcfg, EngineConfig(
        spec_decode="draft", spec_draft_model=f32_draft, spec_k=4, **kw),
        seed=0)
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    assert spec.spec_steps > 0


def test_spec_composes_with_gemma2_class_attention(monkeypatch):
    """Soft-caps + alternating sliding windows + post-norms (the Gemma-2
    shape) flow through the verify block's prefill forward the same as
    through chunked prefill, so ngram spec output must match plain
    greedy exactly."""
    import dataclasses

    g2 = dataclasses.replace(
        CFG, attn_softcap=30.0, final_softcap=20.0, sliding_window=16,
        sliding_pattern="alternate", post_norms=True, norm_plus_one=True)
    prompt = repetitive_prompt() * 2   # long enough to cross the window
    p = SamplingParams(max_tokens=8, temperature=0.0)
    kw = dict(page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=64,
              prefill_buckets=(8, 16, 32, 64), max_model_len=512)
    plain = NativeEngine(g2, EngineConfig(**kw), seed=0).generate(
        prompt, p, "plain")
    import dynamo_tpu.engine.spec as spec_mod
    spec = NativeEngine(g2, EngineConfig(spec_decode="ngram", spec_k=4,
                                         **kw), seed=0)
    # oracle drafts force the verify path (random weights give the real
    # proposer nothing to match after the first token)
    seq_oracle = list(plain)

    def oracle_propose(tokens, k, min_ngram=2, max_ngram=4, max_scan=4096,
                       vocab_size=None):
        done = len(tokens) - len(prompt)
        return seq_oracle[done:done + k]

    monkeypatch.setattr(spec_mod, "ngram_propose", oracle_propose)
    out = spec.generate(prompt, p, "spec")
    assert out == plain
    assert spec.spec_steps > 0


def test_spec_prefix_cache_hashes_unaffected():
    """Sealed-page prefix hashes after a speculative run must equal the
    plain run's (garbage KV from rejected drafts must never leak into
    accounting)."""
    prompt = repetitive_prompt()
    p = SamplingParams(max_tokens=9, temperature=0.0)
    a = make_engine()
    b = make_engine(spec_decode="ngram", spec_k=4)
    ra, rb = "ra", "rb"
    assert a.generate(prompt, p, ra) == b.generate(prompt, p, rb)
    # a second identical request must prefix-hit equally on both engines
    sa = a.scheduler.peek_prefix(prompt)
    sb = b.scheduler.peek_prefix(prompt)
    assert sa == sb


# -- multimodal x speculation --------------------------------------------------

def test_ngram_propose_truncates_at_salt_ids():
    """Prompt-lookup over a salted (multimodal) history must cut the
    proposal at the first out-of-vocab id: the scheduler rewrites image
    span positions to content-hash salts far outside the vocab, and a
    continuation crossing the span would otherwise feed them to the
    verify forward's embedding take (ADVICE r5 high — NaN cascade)."""
    salt = 0x12345678  # representative content-hash salt id
    toks = [11, 12, 13, 14, salt, salt + 1, 21, 22, 11, 12, 13, 14]
    # suffix [11,12,13,14] matches position 0; its continuation IS the
    # salted span — with the vocab bound nothing is proposable
    assert ngram_propose(toks, k=3, vocab_size=256) == []
    # without the bound the salts leak (the pre-fix behaviour)
    assert ngram_propose(toks, k=3)[:2] == [salt, salt + 1]
    # a continuation entering the span mid-way is truncated, not dropped
    toks2 = [11, 12, 13, 14, 77, salt, 21, 11, 12, 13, 14]
    assert ngram_propose(toks2, k=3, vocab_size=256) == [77]


def test_spec_exact_when_draft_crosses_mm_span(monkeypatch):
    """Speculative greedy output for a MULTIMODAL request must stay
    token-identical to plain greedy even when a draft proposal's
    continuation crosses the image span. The oracle proposer below
    mimics a real prompt-lookup match sitting just before a span: two
    correct tokens, then the sequence's actual salt ids. It routes
    through the same vocab_size contract _gather_drafts passes to
    ngram_propose — if the engine stopped passing vocab_size (or
    truncate_to_vocab regressed), the salts reach the verify embedding
    take, NaN the logits, and the outputs diverge."""
    import dynamo_tpu.engine.spec as spec_mod
    from dynamo_tpu.engine.config import VisionConfig

    vcfg = VisionConfig(image_size=28, patch_size=14, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=2)
    cfg = ModelConfig(dtype="float32", max_model_len=256, vision=vcfg)
    n_patch = 4
    prompt = [5, 6, 7, 8] + [0] * n_patch + [9, 10, 11, 12]
    params = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    def make(**kw):
        d = dict(page_size=8, num_pages=64, max_slots=2,
                 max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                 max_model_len=256)
        d.update(kw)
        return NativeEngine(cfg, EngineConfig(**d), seed=0)

    rng = np.random.RandomState(3)
    img = rng.rand(28, 28, 3).astype(np.float32)

    def gen(eng, rid):
        emb = eng.encode_image(img)
        eng.add_request(EngineRequest(rid, prompt, params,
                                      mm_spans=[(4, emb)]))
        seq = next(s for s in eng.scheduler.waiting
                   if s.request_id == rid)
        salts = list(seq.prompt[4:4 + n_patch])
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.token is not None:
                    out.append(ev.token)
        return out, salts

    plain, salts = gen(make(), "plain")
    assert any(not 0 <= s < cfg.vocab_size for s in salts), \
        "admission must salt the span with out-of-vocab ids"

    def span_crossing_propose(tokens, k, min_ngram=2, max_ngram=4,
                              max_scan=4096, vocab_size=None):
        done = len(tokens) - len(prompt)
        cont = plain[done:done + 2] + salts
        return spec_mod.truncate_to_vocab(cont, vocab_size)[:k]

    monkeypatch.setattr(spec_mod, "ngram_propose", span_crossing_propose)
    eng = make(spec_decode="ngram", spec_k=4)
    spec, _ = gen(eng, "spec")
    assert spec == plain
    assert eng.spec_accepted_tokens > 0, \
        "truncated drafts must still exercise the verify path"


# -- pp composition ------------------------------------------------------------

@pytest.mark.parametrize("pp,tp", [(2, 1), (2, 2)])
def test_spec_pp_mesh_exact(pp, tp):
    """spec decode composes with pp meshes: the verify block is one
    prefill-shaped pp_forward (the GPipe stage scan handles Tq > 1), and
    its per-position argmax must replay the single-mesh greedy stream
    token-for-token. Previously rejected at engine init (ROADMAP-1b)."""
    import jax

    from dynamo_tpu.parallel.mesh import make_mesh

    prompt = repetitive_prompt()
    p = SamplingParams(max_tokens=12, temperature=0.0)
    plain = make_engine().generate(prompt, p, "plain")
    mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
    spec = NativeEngine(
        CFG,
        EngineConfig(page_size=8, num_pages=64, max_slots=4,
                     max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                     max_model_len=512, spec_decode="ngram", spec_k=4),
        mesh=mesh, seed=0)
    got = spec.generate(prompt, p, "spec")
    assert got == plain
    # the repetitive prompt must actually drive the pp verify path: the
    # gate falling through to the decode window would also produce the
    # right tokens, but then pp+spec was never exercised
    assert spec.spec_proposed_tokens > 0
    assert spec.spec_accepted_tokens > 0
