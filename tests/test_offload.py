"""Host-DRAM KV tier tests: offload on eviction, onboard on prefix hit.

Models the reference's "+40% TTFT from KV offload to CPU RAM" workload
(multi-turn reuse after eviction, reference docs/architecture.md:91-95,
SURVEY.md §6) at tiny scale: fill HBM, evict via a second workload, then
re-send the first prompt and require identical tokens served via onboarding.
"""
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.offload import CopyStream, HostKvPool
from dynamo_tpu.engine.scheduler import SamplingParams

CFG = ModelConfig(dtype="float32", max_model_len=256)
PAGE = 8


def make_engine(num_pages, host_pages=0, disk_pages=0, disk_dir=None,
                kv_quant=""):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=2,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=256, host_pages=host_pages, disk_pages=disk_pages,
        disk_dir=disk_dir, kv_quant=kv_quant), seed=0)


def test_host_pool_lru():
    pool = HostKvPool(2, (1, 1, 2, 2), np.float32)
    a = np.ones((1, 1, 2, 2), np.float32)
    pool.put(1, a, a)
    pool.put(2, 2 * a, 2 * a)
    assert 1 in pool and 2 in pool
    pool.get(1)              # refresh 1; 2 becomes LRU
    pool.put(3, 3 * a, 3 * a)
    assert 2 not in pool and 1 in pool and 3 in pool
    assert pool.stats.evicted == 1
    k, _ = pool.get(3)
    np.testing.assert_array_equal(k, 3 * a)


def test_offload_onboard_roundtrip_tokens_match():
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    prompt_a = list(range(10, 34))   # 3 pages
    prompt_b = list(range(100, 140))  # 5 pages — evicts A's pages

    # oracle: plenty of HBM, no tier
    big = make_engine(num_pages=64)
    expect_a = big.generate(prompt_a, params, "a")

    # tight HBM + host tier: A -> B (evicts A to host) -> A again (onboards)
    eng = make_engine(num_pages=8, host_pages=16)
    got_a1 = eng.generate(prompt_a, params, "a1")
    assert got_a1 == expect_a
    eng.generate(prompt_b, params, "b")
    assert eng.host_pool.stats.offloaded > 0, "eviction must offload"
    got_a2 = eng.generate(prompt_a, params, "a2")
    assert got_a2 == expect_a
    assert eng.host_pool.stats.onboarded > 0, "re-prefill must onboard"
    assert eng.host_pool.stats.host_hits > 0


def test_onboard_survives_pool_pressure():
    """A pending onboard's host entry must not be LRU-evicted by offloads
    happening between admission and the next step (capacity-1 host pool)."""
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    prompt_a = list(range(10, 34))
    prompt_b = list(range(100, 140))
    expect_a = make_engine(num_pages=64).generate(prompt_a, params, "a")

    eng = make_engine(num_pages=8, host_pages=1)
    eng.generate(prompt_a, params, "a1")
    eng.generate(prompt_b, params, "b")   # evicts A pages; pool keeps 1
    # re-admitting A (host hit on its first page, if retained) triggers more
    # evictions while the onboard is pending — must not crash or corrupt
    got_a2 = eng.generate(prompt_a, params, "a2")
    assert got_a2 == expect_a


def test_disk_tier_spill_and_promote(tmp_path):
    """Three-tier ladder (HBM -> DRAM -> disk, reference kv/storage.rs):
    with a 2-page DRAM slab, workload B's eviction pressure pushes A's
    pages down to disk; re-sending A promotes them back and produces
    identical tokens."""
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    prompt_a = list(range(10, 34))    # 3 pages
    prompt_b = list(range(100, 140))  # 5 pages
    expect_a = make_engine(num_pages=64).generate(prompt_a, params, "a")

    # 6 HBM pages: B (5 prompt + 1 decode page) must reclaim every one of
    # A's 3 sealed pages -> 3 offloads into a 2-page DRAM slab -> >=1 spill
    eng = make_engine(num_pages=6, host_pages=2, disk_pages=16,
                      disk_dir=str(tmp_path))
    assert eng.generate(prompt_a, params, "a1") == expect_a
    eng.generate(prompt_b, params, "b")   # evicts A: DRAM -> disk cascade
    eng._copy_stream.drain()  # offload copies are flush-behind
    st = eng.host_pool.stats
    assert st.disk_offloaded > 0, "DRAM pressure must spill to disk"
    got_a2 = eng.generate(prompt_a, params, "a2")
    assert got_a2 == expect_a
    assert st.disk_hits > 0, "re-prefill must promote from the disk tier"


def test_kv_quant_pages_survive_host_and_disk_tiers(tmp_path):
    """int8 pages spill and promote through the full tier ladder in
    their QUANTIZED representation (int8 slabs + f32 scale slabs,
    checksums over both) and decode tokens stay identical to the
    int8 no-tier oracle — the acceptance bar's offload leg."""
    params = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    prompt_a = list(range(10, 34))    # 3 pages
    prompt_b = list(range(100, 140))  # 5 pages
    expect_a = make_engine(num_pages=64,
                           kv_quant="int8").generate(prompt_a, params, "a")

    eng = make_engine(num_pages=6, host_pages=2, disk_pages=16,
                      disk_dir=str(tmp_path), kv_quant="int8")
    # tier slabs store the device representation: int8 values, f32 scales
    assert eng.host_pool.k_slab.dtype == np.int8
    assert eng.host_pool.ks_slab is not None
    assert eng.host_pool.ks_slab.dtype == np.float32
    assert eng.generate(prompt_a, params, "a1") == expect_a
    eng.generate(prompt_b, params, "b")   # evicts A: DRAM -> disk cascade
    eng._copy_stream.drain()
    st = eng.host_pool.stats
    assert st.offloaded > 0 and st.disk_offloaded > 0
    got_a2 = eng.generate(prompt_a, params, "a2")
    assert got_a2 == expect_a
    assert st.disk_hits > 0 and st.onboarded > 0


def test_host_pool_scale_rot_is_caught():
    """The capture checksum covers the SCALE rows too: flipping a scale
    byte (values intact) must still quarantine on read — a corrupted
    scale silently rescales every token in the page."""
    from dynamo_tpu.runtime.integrity import STATS as INTEGRITY
    INTEGRITY.reset()
    pool = HostKvPool(2, (1, 1, 2, 2), np.int8, scale_shape=(1, 1, 2))
    k = np.ones((1, 1, 2, 2), np.int8)
    s = np.full((1, 1, 2), 0.5, np.float32)
    pool.put(7, k, k, s, s)
    got = pool.get(7)
    assert got is not None and len(got) == 4
    pool.ks_slab[0].view(np.uint8)[0] ^= 0xFF   # rot the scale at rest
    assert pool.get(7) is None                  # quarantined, never served
    assert INTEGRITY.quarantined == 1
    INTEGRITY.reset()


def test_offload_disabled_by_default():
    eng = make_engine(num_pages=10)
    assert eng.host_pool is None
    params = SamplingParams(max_tokens=3, temperature=0.0, ignore_eos=True)
    assert len(eng.generate(list(range(20)), params, "x")) == 3


def test_copy_stream_settle_is_per_hash():
    """VERDICT r3 weak #4: admission must wait only for in-flight copies
    of the hashes its prefix walk touches — an unrelated offload burst
    (slow D2H) cannot stall it."""
    import time

    pool = HostKvPool(4, (1, 1, 2, 2), np.float32)
    cs = CopyStream(pool)

    class SlowPages:
        """np-convertible payload whose D2H 'copy' takes ~0.5s."""

        def __init__(self, arr, delay):
            self.arr = arr
            self.delay = delay

        def __array__(self, dtype=None, copy=None):
            time.sleep(self.delay)
            return self.arr

    arr = np.zeros((1, 1, 1, 2, 2), np.float32)
    try:
        cs.submit({"k": SlowPages(arr, 0.5), "v": arr}, [111])
        t0 = time.perf_counter()
        cs.settle([222, 333])       # unrelated hashes: no wait
        assert time.perf_counter() - t0 < 0.25
        t0 = time.perf_counter()
        cs.settle([333, 111])       # overlapping hash: waits for the copy
        waited = time.perf_counter() - t0
        assert waited > 0.1
        assert 111 in pool
    finally:
        cs.close()


def test_scheduler_settles_only_walk_hashes():
    """The prefix walk hands exactly its candidate hash chain to
    settle_hashes before any tier lookup."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.kv_cache import page_hash
    from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler

    cfg = EngineConfig(page_size=4, num_pages=16, max_slots=2,
                       max_prefill_chunk=16, prefill_buckets=(4, 8, 16),
                       max_model_len=64)
    sched = Scheduler(cfg)
    seen = []
    sched.settle_hashes = seen.append
    prompt = list(range(1, 11))     # 10 tokens -> 2 full pages
    sched.add_request(EngineRequest("r", prompt))
    h1 = page_hash(0, prompt[:4])
    h2 = page_hash(h1, prompt[4:8])
    assert seen == [[h1, h2]]
