"""DYN_LOG env-filtered logging + layered settings (VERDICT r3 #8).

Reference analogues: lib/runtime/src/logging.rs:16-120 (RUST_LOG-grammar
level filters + JSONL mode) and lib/runtime/src/config.rs:81-105 (figment
layering defaults <- TOML <- DYN_* env).
"""
import json
import logging

import pytest

from dynamo_tpu.utils.logconfig import (
    JsonlFormatter, configure_logging, parse_filter,
)
from dynamo_tpu.utils.settings import load_settings


@pytest.fixture(autouse=True)
def _restore_logging():
    root = logging.getLogger()
    saved = (list(root.handlers), root.level)
    yield
    root.handlers[:], lvl = saved[0], saved[1]
    root.setLevel(lvl)
    for name in ("dynamo_tpu.engine", "dynamo_tpu.kv_router"):
        logging.getLogger(name).setLevel(logging.NOTSET)


def test_parse_filter_grammar():
    default, per = parse_filter(
        "info,dynamo_tpu.engine=debug,dynamo_tpu.kv_router=warn")
    assert default == logging.INFO
    assert per == {"dynamo_tpu.engine": logging.DEBUG,
                   "dynamo_tpu.kv_router": logging.WARNING}
    # unknown directives are ignored, not fatal
    default, per = parse_filter("bogus,dynamo_tpu.engine=notalevel,error")
    assert default == logging.ERROR
    assert per == {}


def test_dyn_log_per_module_filter(monkeypatch):
    monkeypatch.setenv("DYN_LOG", "warning,dynamo_tpu.engine=debug")
    configure_logging()
    eng = logging.getLogger("dynamo_tpu.engine")
    other = logging.getLogger("dynamo_tpu.kv_router")
    assert eng.isEnabledFor(logging.DEBUG)
    assert not other.isEnabledFor(logging.INFO)  # root default = warning
    assert other.isEnabledFor(logging.WARNING)
    # reconfigure without the directive: the old per-module level resets
    monkeypatch.setenv("DYN_LOG", "warning")
    configure_logging()
    assert not eng.isEnabledFor(logging.DEBUG)


def test_jsonl_sink(monkeypatch, capsys):
    monkeypatch.setenv("DYN_LOG", "info")
    monkeypatch.setenv("DYN_LOGGING_JSONL", "1")
    configure_logging()
    logging.getLogger("dynamo_tpu.test").info("hello %s", "world")
    line = capsys.readouterr().err.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["level"] == "INFO"
    assert rec["target"] == "dynamo_tpu.test"
    assert rec["message"] == "hello world"
    assert rec["ts"].endswith("Z")


def test_jsonl_formatter_exception():
    f = JsonlFormatter()
    try:
        raise ValueError("boom")
    except ValueError:
        import sys
        rec = logging.LogRecord("t", logging.ERROR, __file__, 1, "bad", (),
                                sys.exc_info())
    out = json.loads(f.format(rec))
    assert "ValueError: boom" in out["exception"]


def test_settings_layering(tmp_path):
    defaults = {"control_plane": {"host": "127.0.0.1", "port": 6230},
                "lease_ttl_s": 10.0, "name": "svc"}
    cfg = tmp_path / "dyn.toml"
    cfg.write_text('lease_ttl_s = 20.0\n[control_plane]\nport = 7000\n')
    s = load_settings(defaults, config_file=str(cfg), environ={
        "DYN_CONTROL_PLANE__PORT": "9000",
        "DYN_NAME": '"prod"',
        "DYN_UNRELATED_JUNK": "1",       # not in defaults: must not leak
        "DYN_COORD_ADDR": "10.0.0.1:1",  # consumed elsewhere: ignored
    })
    assert s.control_plane.port == 9000          # env beats file
    assert s.control_plane.host == "127.0.0.1"   # default survives
    assert s.lease_ttl_s == 20.0                 # file beats default
    assert s.name == "prod"                      # JSON-parsed env string
    assert "unrelated_junk" not in s
    assert "coord_addr" not in s


def test_settings_yaml_and_env_config(tmp_path):
    cfg = tmp_path / "dyn.yaml"
    cfg.write_text("a:\n  b: 5\n")
    s = load_settings({"a": {"b": 1, "c": 2}}, environ={
        "DYN_CONFIG": str(cfg)})
    assert s.a.b == 5 and s.a.c == 2


def test_settings_env_type_parsing():
    s = load_settings({"flag": False, "n": 1, "ratio": 0.5, "raw": "x"},
                      environ={"DYN_FLAG": "true", "DYN_N": "42",
                               "DYN_RATIO": "0.25", "DYN_RAW": "plain:text"})
    assert s.flag is True and s.n == 42 and s.ratio == 0.25
    assert s.raw == "plain:text"


def test_settings_parent_scalar_and_nested_child_coexist():
    """A parent-key scalar env and a nested child env must not crash or
    silently drop the child; the deeper override wins (code-review r4)."""
    defaults = {"control_plane": {"host": "127.0.0.1", "port": 6230}}
    s = load_settings(defaults, environ={
        "DYN_CONTROL_PLANE": "10.0.0.1:7411",   # ill-formed scalar-for-dict
        "DYN_CONTROL_PLANE__PORT": "9000",
    })
    assert s.control_plane.port == 9000
    assert s.control_plane.host == "127.0.0.1"
