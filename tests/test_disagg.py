"""Disaggregated prefill/decode tests.

Mirrors the reference's disagg flow (SURVEY.md §3.3): decision router,
durable prefill queue, decode-side up-front allocation, prefill-only engine
runs, inter-mesh KV page transfer, completion notify — all on the virtual
CPU mesh with the in-memory control plane.
"""
import asyncio

import jax
import pytest

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
    PrefillQueue, PrefillWorker, RemotePrefillRequest,
)
from dynamo_tpu.disagg.router import config_key
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
from dynamo_tpu.llm.worker import NativeEngineWorker
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine(mesh=None):
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), mesh=mesh, seed=0)


def pre_request(rid, prompt, max_tokens=6):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True))


# -- router decision ----------------------------------------------------------

def test_disagg_decision():
    r = DisaggregatedRouter(max_local_prefill_length=1000,
                            max_prefill_queue_size=2)
    assert r.prefill_remote(prefill_length=2000, prefix_hit_length=0,
                            queue_depth=0)
    # prefix hit brings the un-cached work under the threshold
    assert not r.prefill_remote(2000, 1500, 0)
    # queue backed up: keep it local
    assert not r.prefill_remote(2000, 0, 2)
    assert not r.prefill_remote(500, 0, 0)


def test_disagg_threshold_live_reload():
    async def main():
        plane = MemoryPlane()
        r = DisaggregatedRouter(max_local_prefill_length=1000, model="m")
        task = r.start_watching(plane.kv)
        await asyncio.sleep(0.05)
        await plane.kv.put(config_key("m"),
                           b'{"max_local_prefill_length": 10}')
        for _ in range(100):
            if r.max_local_prefill_length == 10:
                break
            await asyncio.sleep(0.01)
        task.cancel()
        return r.max_local_prefill_length

    assert asyncio.run(main()) == 10


def test_prefill_queue_roundtrip():
    async def main():
        plane = MemoryPlane()
        q = PrefillQueue(plane.messaging, "ns", "model-a")
        req = RemotePrefillRequest(
            engine_id="e1", request_id="r1", token_ids=[1, 2, 3],
            page_ids=[4, 5], num_cached_tokens=0, page_size=8,
            sampling=SamplingOptions(temperature=0.5),
            notify_subject="disagg.prefill_done.e1")
        await q.enqueue(req)
        assert await q.depth() == 1
        got = await q.dequeue(timeout=1.0)
        assert await q.depth() == 0
        empty = await q.dequeue(timeout=0.05)
        return req, got, empty

    req, got, empty = asyncio.run(main())
    assert got == req
    assert empty is None


# -- engine-level remote prefill primitives -----------------------------------

def test_engine_prefill_only_parks_and_extracts():
    eng = make_engine()
    prompt = list(range(10, 30))  # 20 tokens -> 3 pages (page 8)
    eng.add_request(EngineRequest("p1", prompt, SamplingParams(
        max_tokens=4, ignore_eos=True), prefill_only=True))
    outs = []
    while eng.has_work():
        outs.extend(eng.step())
    assert len(outs) == 1 and outs[0].finish_reason == "prefill_done"
    assert outs[0].token is not None
    seq = eng.scheduler.parked["p1"]
    assert len(seq.pages) == 3  # ceil(20/8)
    pages = eng.extract_pages(seq.pages)
    # page-count bucketed per the scheduler's ladder: [L, Hkv, Nb, ps, hd]
    from dynamo_tpu.engine.scheduler import next_bucket
    nb = next_bucket(3, eng.scheduler.page_buckets)
    assert pages["k"].shape == (CFG.num_layers, CFG.num_kv_heads, nb, PAGE,
                                CFG.head_dim)
    eng.release_parked("p1")
    assert "p1" not in eng.scheduler.parked


def test_engine_remote_alloc_inject_activate_matches_local():
    prompt = list(range(40, 60))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    prefill_eng = make_engine()
    decode_eng = make_engine()
    # decode side: allocate up-front
    alloc = decode_eng.allocate_remote(EngineRequest("r", prompt, params))
    assert alloc is not None and len(alloc.page_ids) == 3
    # prefill side: run prefill-only, extract pages
    prefill_eng.add_request(
        EngineRequest("r", prompt, params, prefill_only=True))
    outs = []
    while prefill_eng.has_work():
        outs.extend(prefill_eng.step())
    first = outs[0].token
    seq = prefill_eng.scheduler.parked["r"]
    pages = prefill_eng.extract_pages(seq.pages)
    # transfer: same process, device_put onto the decode cache sharding
    k = jax.device_put(pages["k"], decode_eng.cache_sharding)
    v = jax.device_put(pages["v"], decode_eng.cache_sharding)
    decode_eng.inject_pages(alloc.page_ids, k, v)
    prefill_eng.release_parked("r")
    # activate and decode to completion
    decode_eng.activate_remote("r", first)
    toks = [first]
    while decode_eng.has_work():
        for ev in decode_eng.step():
            if ev.token is not None:
                toks.append(ev.token)
    assert toks == expect


def test_transfer_receiver_deregisters_during_staging():
    """Regression for the await-interleaving race in LocalTransferBackend:
    the chaos-mode staging hop suspends, and the receiver registry can
    lose the decode engine while the event loop is yielded. The backend
    must re-read the registry after the hop and fail loudly instead of
    submitting the injection through the pre-await corpse handle."""
    from dynamo_tpu.runtime import faults
    from dynamo_tpu.runtime.faults import FaultSchedule, FaultSpec

    prompt = list(range(40, 60))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    class ChurnTransfer(LocalTransferBackend):
        async def _verified_stage(self, request_id, ids, k_pages, v_pages,
                                  k_scale=None, v_scale=None):
            staged = await LocalTransferBackend._verified_stage(
                request_id, ids, k_pages, v_pages, k_scale, v_scale)
            # the watch pump culls the decode worker while the staging
            # hop held the loop — exactly the interleaving under test
            self.unregister("dec-0")
            return staged

    async def main():
        prefill_eng = make_engine()
        decode_eng = make_engine()
        alloc = decode_eng.allocate_remote(EngineRequest("r", prompt, params))
        assert alloc is not None
        prefill_eng.add_request(
            EngineRequest("r", prompt, params, prefill_only=True))
        while prefill_eng.has_work():
            prefill_eng.step()
        pages = prefill_eng.extract_pages(
            prefill_eng.scheduler.parked["r"].pages)
        transfer = ChurnTransfer()
        transfer.register("dec-0", NativeEngineWorker(decode_eng))
        # arm the staging site with a never-firing spec (p=0): the pages
        # route device -> host -> device, which is where the await lives,
        # but no corruption is ever injected
        faults.REGISTRY.arm("remote_transfer.fetch_page",
                            FaultSchedule(0, [FaultSpec("corrupt", p=0.0)]))
        try:
            with pytest.raises(KeyError, match="deregistered during"):
                await transfer.send_pages(
                    "dec-0", "r", alloc.page_ids, pages["k"], pages["v"],
                    alloc_epoch=alloc.alloc_epoch)
        finally:
            faults.REGISTRY.disarm()

    asyncio.run(main())


# -- full worker-level disagg flow --------------------------------------------

async def _drive(worker_gen):
    toks, reason = [], None
    async for frame in worker_gen:
        toks.extend(frame.get("token_ids", ()))
        if frame.get("finish_reason") not in (None, "prefill_done"):
            reason = frame["finish_reason"]
    return toks, reason


def _build_stack(plane, decode_mesh=None, prefill_mesh=None,
                 local_threshold=4):
    transfer = LocalTransferBackend()
    queue = PrefillQueue(plane.messaging, "ns", "tiny")
    router = DisaggregatedRouter(max_local_prefill_length=local_threshold,
                                 max_prefill_queue_size=4, model="tiny")
    decode = DisaggDecodeWorker(
        make_engine(decode_mesh), plane.messaging, router, queue,
        worker_id="dec-0", prefill_timeout_s=30.0)
    transfer.register("dec-0", decode)
    prefill = PrefillWorker(
        NativeEngineWorker(make_engine(prefill_mesh)), queue, transfer,
        plane.messaging)
    return decode, prefill


def test_disagg_worker_e2e_matches_aggregated():
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    async def main():
        plane = MemoryPlane()
        decode, prefill = _build_stack(plane)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("r1", prompt).model_dump(
                    exclude_none=True), Context("r1")))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, reason, decode.remote_prefills, prefill.completed

    toks, reason, n_remote, n_prefills = asyncio.run(main())
    assert n_remote == 1 and n_prefills == 1
    assert reason == "length"
    assert toks == expect


def test_disagg_short_prompt_stays_local():
    prompt = list(range(4))

    async def main():
        plane = MemoryPlane()
        decode, prefill = _build_stack(plane, local_threshold=100)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("s1", prompt).model_dump(
                    exclude_none=True), Context("s1")))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, decode.remote_prefills, decode.local_prefills

    toks, n_remote, n_local = asyncio.run(main())
    assert n_remote == 0 and n_local == 1
    assert len(toks) == 6


def test_disagg_tp_mismatch_relayout():
    """Prefill tp=1, decode tp=2: device_put reshards (kv_rearrange role)."""
    devs = jax.devices()
    assert len(devs) >= 2
    decode_mesh = make_mesh(tp=2, devices=devs[:2])
    prompt = list(range(60, 80))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    # oracle: aggregated engine on the SAME decode mesh (identical layout)
    expect = make_engine(decode_mesh).generate(prompt, params, "direct")

    async def main():
        plane = MemoryPlane()
        decode, prefill = _build_stack(plane, decode_mesh=decode_mesh)
        await decode.start()
        await prefill.start()
        try:
            toks, _ = await _drive(
                decode.generate(pre_request("t1", prompt).model_dump(
                    exclude_none=True), Context("t1")))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, decode.remote_prefills

    toks, n_remote = asyncio.run(main())
    assert n_remote == 1
    assert toks == expect


def test_disagg_remote_first_token_hidden_stop_not_emitted():
    """A hidden stop id sampled as the remote first token must not leak to
    the client (parity with the local path's _postprocess)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    first = make_engine().generate(prompt, params, "oracle")[0]

    async def main():
        plane = MemoryPlane()
        decode, prefill = _build_stack(plane)
        await decode.start()
        await prefill.start()
        try:
            req = PreprocessedRequest(
                request_id="h1", token_ids=prompt,
                stop=StopConditions(max_tokens=6, ignore_eos=True,
                                    stop_token_ids_hidden=[first]))
            toks, reason = await _drive(
                decode.generate(req.model_dump(exclude_none=True),
                                Context("h1")))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, reason, decode.remote_prefills

    toks, reason, n_remote = asyncio.run(main())
    assert n_remote == 1
    assert toks == []
    assert reason == "stop"


def test_disagg_client_abort_cancels_remote_prefill():
    """Client disconnect while the remote prefill is queued/running must
    cancel BOTH sides: the decode stream ends CANCELLED and releases its
    up-front allocation, and the prefill fleet drops the item — whether it
    is still queued (skip on dequeue) or mid-run (abort) — without ever
    transferring or redelivering it."""
    from dynamo_tpu.engine.kv_cache import PageAllocator  # noqa: F401

    prompt = list(range(100, 120))

    class GatedTransfer(LocalTransferBackend):
        def __init__(self):
            super().__init__()
            self.gate = asyncio.Event()
            self.sent = []

        async def send_pages(self, engine_id, request_id, *a, **k):
            await self.gate.wait()
            self.sent.append(request_id)
            await super().send_pages(engine_id, request_id, *a, **k)

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=30.0)
        transfer = GatedTransfer()
        transfer.register("dec-0", decode)
        # one handler slot: item A occupies it mid-run, item B stays queued
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, max_inflight=1,
            lease_s=30.0)
        await decode.start()
        await prefill.start()

        async def drive(rid, ctx):
            toks, reason = [], None
            async for frame in decode.generate(
                    pre_request(rid, prompt).model_dump(exclude_none=True),
                    ctx):
                toks.extend(frame.get("token_ids", ()))
                if frame.get("finish_reason") not in (None, "prefill_done"):
                    reason = frame["finish_reason"]
            return toks, reason

        ctx_a, ctx_b = Context("abortA"), Context("abortB")
        task_a = asyncio.create_task(drive("abortA", ctx_a))
        # A is being handled (held at the transfer gate) before B arrives
        deadline = asyncio.get_event_loop().time() + 20
        while "abortA" not in prefill._handling:
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        task_b = asyncio.create_task(drive("abortB", ctx_b))
        while await queue.depth() < 1:    # B parked in the queue
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)

        # both clients disconnect
        ctx_a.stop_generating()
        ctx_b.stop_generating()
        (toks_a, reason_a), (toks_b, reason_b) = await asyncio.wait_for(
            asyncio.gather(task_a, task_b), 30)
        assert (toks_a, reason_a) == ([], "cancelled")
        assert (toks_b, reason_b) == ([], "cancelled")

        # mid-run item A was aborted at the gate; open it and give the
        # worker time — the transfer must never happen, and queued item B
        # must be skipped on dequeue, not run
        transfer.gate.set()
        for _ in range(100):
            if prefill.cancelled >= 2:
                break
            await asyncio.sleep(0.02)
        assert prefill.cancelled == 2, prefill.cancelled
        assert transfer.sent == []
        assert prefill.completed == 0

        # the decode side released its up-front allocations
        def remote_state(eng):
            return (len(eng.scheduler.remote),
                    eng.scheduler.allocator.num_free)
        for _ in range(100):
            n_remote, _free = await decode.submit(remote_state)
            if n_remote == 0:
                break
            await asyncio.sleep(0.02)
        n_remote, num_free = await decode.submit(remote_state)
        assert n_remote == 0
        assert num_free == decode.engine.cfg.num_pages

        # and nothing redelivers later (leases were settled by the cancel)
        await asyncio.sleep(0.2)
        assert await queue.depth() == 0
        await prefill.stop()
        await decode.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_disagg_prefill_timeout_broadcasts_cancel():
    """A remote prefill the decode side TIMES OUT on (not a client
    disconnect) must also broadcast PrefillCancel: without it, the
    abandoned prefill keeps burning an engine slot to completion even
    though its transfer can only be rejected. The decode stream itself
    falls back to a local prefill and still completes."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "oracle")

    class HoldTransfer(LocalTransferBackend):
        """Never completes: the decode-side prefill_timeout_s fires."""

        async def send_pages(self, *a, **k):
            await asyncio.Event().wait()

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=1.0)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, HoldTransfer(),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=30.0)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = [], None
            async for frame in decode.generate(
                    pre_request("rt", prompt).model_dump(exclude_none=True),
                    Context("rt")):
                toks.extend(frame.get("token_ids", ()))
                if frame.get("finish_reason") not in (None, "prefill_done"):
                    reason = frame["finish_reason"]
            # timeout -> cancel broadcast -> local fallback, same tokens
            assert reason == "length" and toks == expect
            deadline = asyncio.get_event_loop().time() + 20
            while prefill.cancelled < 1:
                assert asyncio.get_event_loop().time() < deadline, \
                    "timed-out prefill was never cancelled fleet-side"
                await asyncio.sleep(0.02)
            assert prefill.cancelled == 1
            assert prefill.completed == 0
            # the cancel settled the lease: nothing redelivers later
            await asyncio.sleep(0.2)
            assert await queue.depth() == 0
        finally:
            await prefill.stop()
            await decode.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_prefill_queue_touch_extends_lease():
    """queue.touch re-arms a leased item's redelivery deadline (the
    transfer leg's in-progress ack); an expired token reports False."""
    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        req = RemotePrefillRequest(
            engine_id="dec-0", request_id="q1", token_ids=[1, 2, 3],
            page_ids=[0], page_size=8)
        await queue.enqueue(req)
        got = await queue.dequeue_leased(timeout=1.0, lease_s=0.3)
        assert got is not None
        _item, token = got
        await asyncio.sleep(0.2)
        assert await queue.touch(token, lease_s=0.6)  # re-armed
        await asyncio.sleep(0.3)          # past the ORIGINAL deadline
        assert await queue.depth() == 0   # not redelivered: touch held it
        assert plane.messaging.redeliveries == 0
        await asyncio.sleep(0.5)          # past the touched deadline too
        assert await queue.depth() == 1   # un-acked: redelivered now
        got2 = await queue.dequeue_leased(timeout=1.0, lease_s=5.0)
        assert got2 is not None and got2[0].request_id == "q1"
        # the first token is dead after redelivery: touch says so
        assert not await queue.touch(token, lease_s=1.0)
        await queue.ack(got2[1])

    asyncio.run(asyncio.wait_for(main(), 30))


def test_disagg_prefill_worker_death_mid_item_redelivers():
    """Satellite: a prefill worker that dies after dequeue but before
    completion must NOT lose the item — the lease expires and a surviving
    worker re-runs it; the decode stream completes oracle-exact."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    class WedgedTransfer(LocalTransferBackend):
        async def send_pages(self, *a, **k):
            await asyncio.Event().wait()

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=60.0)
        transfer = LocalTransferBackend()
        transfer.register("dec-0", decode)
        doomed = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, WedgedTransfer(),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=0.3)
        await decode.start()
        await doomed.start()

        task = asyncio.create_task(_drive(decode.generate(
            pre_request("r1", prompt).model_dump(exclude_none=True),
            Context("r1"))))
        deadline = asyncio.get_event_loop().time() + 20
        while "r1" not in doomed._handling:   # dequeued, wedged mid-item
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        await doomed.stop()                   # dies holding the item

        survivor = await PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=10.0).start()
        toks, reason = await asyncio.wait_for(task, 60)
        redelivered = plane.messaging.redeliveries
        completed = survivor.completed
        await survivor.stop()
        await decode.stop()
        return toks, reason, redelivered, completed

    toks, reason, redelivered, completed = asyncio.run(
        asyncio.wait_for(main(), 120))
    assert redelivered >= 1
    assert completed == 1
    assert reason == "length"
    assert toks == expect


def test_disagg_prefill_failure_falls_back_local():
    """Transfer failure -> decode releases the allocation and recomputes."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    class BrokenTransfer(LocalTransferBackend):
        async def send_pages(self, *a, **k):
            raise RuntimeError("link down")

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=4)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=30.0)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, BrokenTransfer(),
            plane.messaging)
        await decode.start()
        await prefill.start()
        try:
            toks, reason = await _drive(
                decode.generate(pre_request("f1", prompt).model_dump(
                    exclude_none=True), Context("f1")))
        finally:
            await prefill.stop()
            await decode.stop()
        return toks, reason, prefill.failed, decode.local_prefills

    toks, reason, n_failed, n_local = asyncio.run(main())
    assert n_failed == 1 and n_local == 1
    assert reason == "length"
    assert toks == expect


def test_expired_deadline_dropped_at_dequeue_not_prefilled():
    """Satellite: the client deadline rides into the queued item
    (RemotePrefillRequest.deadline_unix); a prefill worker dequeuing an
    already-expired item drops it — lease settled (no redelivery), decode
    side notified immediately — instead of burning an engine slot on a
    stream that is already dead."""
    import time

    from dynamo_tpu.disagg.protocols import PrefillCompletion

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        notify = "ns.completions.dec-0"
        sub = await plane.messaging.subscribe(notify)
        prefill = PrefillWorker(
            NativeEngineWorker(make_engine()), queue,
            LocalTransferBackend(), plane.messaging, dequeue_timeout_s=0.1)
        await queue.enqueue(RemotePrefillRequest(
            engine_id="dec-0", request_id="r-expired",
            token_ids=list(range(100, 120)), page_ids=[0, 1, 2],
            page_size=PAGE, notify_subject=notify,
            deadline_unix=time.time() - 1.0))   # expired while queued
        await prefill.start()
        agen = sub.__aiter__()
        _subject, payload = await asyncio.wait_for(agen.__anext__(), 30)
        done = PrefillCompletion.model_validate_json(payload)
        counters = (prefill.expired, prefill.completed, prefill.failed)
        depth = await queue.depth()
        await prefill.stop()
        return done, counters, depth, plane.messaging.redeliveries

    done, (expired, completed, failed), depth, redelivered = asyncio.run(
        asyncio.wait_for(main(), 60))
    assert done.request_id == "r-expired"
    assert done.error and "deadline" in done.error
    assert expired == 1 and completed == 0 and failed == 0
    assert depth == 0 and redelivered == 0   # acked: settled, not re-leased


def test_prefill_worker_drain_releases_unfinished_items():
    """Planned-maintenance drain of a prefill worker: it stops consuming
    the queue, waits out the deadline, and leaves unfinished items to
    their LEASES — no ack, so they are re-leased to a surviving worker
    and the decode stream completes oracle-exact (rolling-restart leg of
    docs/RESILIENCE.md; unplanned death is the sibling test above)."""
    prompt = list(range(100, 120))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    expect = make_engine().generate(prompt, params, "direct")

    class WedgedTransfer(LocalTransferBackend):
        async def send_pages(self, *a, **k):
            await asyncio.Event().wait()

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=16)
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-0", prefill_timeout_s=60.0)
        transfer = LocalTransferBackend()
        transfer.register("dec-0", decode)
        draining = PrefillWorker(
            NativeEngineWorker(make_engine()), queue, WedgedTransfer(),
            plane.messaging, dequeue_timeout_s=0.1, lease_s=0.3)
        await decode.start()
        await draining.start()

        task = asyncio.create_task(_drive(decode.generate(
            pre_request("r1", prompt).model_dump(exclude_none=True),
            Context("r1"))))
        deadline = asyncio.get_event_loop().time() + 20
        while "r1" not in draining._handling:   # dequeued, wedged mid-item
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        summary = await draining.drain(timeout_s=0.2)

        survivor = await PrefillWorker(
            NativeEngineWorker(make_engine()), queue, transfer,
            plane.messaging, dequeue_timeout_s=0.1, lease_s=10.0).start()
        toks, reason = await asyncio.wait_for(task, 60)
        completed = survivor.completed
        await survivor.stop()
        await decode.stop()
        return summary, toks, reason, completed

    summary, toks, reason, completed = asyncio.run(
        asyncio.wait_for(main(), 120))
    assert summary["re_leased"] == 1     # cut at the drain deadline
    assert completed == 1                # survivor re-ran the re-leased item
    assert reason == "length"
    assert toks == expect
