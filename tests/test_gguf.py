"""GGUF sourcing tests (VERDICT r2 next #10; reference lib/llm/src/gguf.rs).

A self-contained GGUF *writer* lives in the test so the parser is validated
against independently-generated files (container layout per the public GGUF
spec), covering: typed metadata (scalars, strings, arrays), F32/F16/Q8_0
tensors with alignment, config mapping, params loading into a generating
engine, the embedded tokenizer, and ModelDeploymentCard.from_gguf.
"""
import struct

import numpy as np
import pytest

from dynamo_tpu.llm.gguf import (
    GGUFFile, GGUFTokenizer, config_from_gguf, load_params_from_gguf,
)

ALIGN = 32


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<Q", len(b)) + b


def _pack_value(vtype: int, v) -> bytes:
    fmts = {0: "<B", 1: "<b", 2: "<H", 3: "<h", 4: "<I", 5: "<i", 6: "<f",
            7: "<?", 10: "<Q", 11: "<q", 12: "<d"}
    if vtype in fmts:
        return struct.pack(fmts[vtype], v)
    if vtype == 8:
        return _pack_str(v)
    raise ValueError(vtype)


def write_gguf(path, metadata, tensors):
    """metadata: {key: (vtype, value) | (9, (etype, [values]))};
    tensors: {name: (ggml_type, np_array_rowmajor, raw_bytes)}."""
    out = bytearray()
    out += b"GGUF" + struct.pack("<I", 3)
    out += struct.pack("<QQ", len(tensors), len(metadata))
    for key, (vtype, value) in metadata.items():
        out += _pack_str(key)
        out += struct.pack("<I", vtype)
        if vtype == 9:
            etype, values = value
            out += struct.pack("<I", etype) + struct.pack("<Q", len(values))
            for v in values:
                out += _pack_value(etype, v)
        else:
            out += _pack_value(vtype, value)
    offset = 0
    blobs = []
    for name, (gtype, arr, raw) in tensors.items():
        dims = list(reversed(arr.shape))  # ne order: fastest first
        out += _pack_str(name)
        out += struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, offset)
        blobs.append((offset, raw))
        offset += (len(raw) + ALIGN - 1) // ALIGN * ALIGN
    pad = (-len(out)) % ALIGN
    out += b"\x00" * pad
    data_start = len(out)
    out += b"\x00" * offset
    for off, raw in blobs:
        out[data_start + off:data_start + off + len(raw)] = raw
    with open(path, "wb") as f:
        f.write(out)


def _f32(arr):
    return (0, arr, np.ascontiguousarray(arr, np.float32).tobytes())


def _f16(arr):
    return (1, arr, np.ascontiguousarray(arr, np.float16).tobytes())


def _q8_0(arr):
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1, 32)
    scale = np.abs(flat).max(axis=1, keepdims=True) / 127.0
    scale[scale == 0] = 1.0
    qs = np.clip(np.round(flat / scale), -127, 127).astype(np.int8)
    raw = b"".join(
        scale[i].astype(np.float16).tobytes() + qs[i].tobytes()
        for i in range(flat.shape[0]))
    return (8, arr, raw)


D, HEADS, KV, HD, L, F = 32, 4, 2, 8, 2, 64


def _vocab():
    toks = ["<unk>", "<s>", "</s>"]
    toks += [f"<0x{b:02X}>" for b in range(256)]
    toks += ["▁hello", "▁world", "▁the", "lo", "wor"]
    return toks


def make_tiny_gguf(path, embed_type=_f32):
    rng = np.random.RandomState(0)
    toks = _vocab()
    vocab = len(toks)

    def r(*shape):
        return (rng.randn(*shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": embed_type(r(vocab, D)),
        "output_norm.weight": _f32(np.ones(D, np.float32)),
        "output.weight": _f16(r(vocab, D)),
    }
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.attn_q.weight": _f32(r(HEADS * HD, D)),
            f"blk.{i}.attn_k.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_v.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_output.weight": _q8_0(r(D, HEADS * HD)),
            f"blk.{i}.ffn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.ffn_gate.weight": _f32(r(F, D)),
            f"blk.{i}.ffn_up.weight": _f32(r(F, D)),
            f"blk.{i}.ffn_down.weight": _f32(r(D, F)),
        })
    metadata = {
        "general.architecture": (8, "llama"),
        "general.name": (8, "tiny-gguf"),
        "llama.embedding_length": (4, D),
        "llama.block_count": (4, L),
        "llama.feed_forward_length": (4, F),
        "llama.attention.head_count": (4, HEADS),
        "llama.attention.head_count_kv": (4, KV),
        "llama.attention.layer_norm_rms_epsilon": (6, 1e-5),
        "llama.rope.freq_base": (6, 10000.0),
        "llama.context_length": (4, 256),
        "tokenizer.ggml.model": (8, "llama"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.bos_token_id": (4, 1),
        "tokenizer.ggml.eos_token_id": (4, 2),
    }
    write_gguf(path, metadata, tensors)
    return toks


def test_parse_config_and_metadata(tmp_path):
    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    cfg = config_from_gguf(g)
    assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads,
            cfg.num_kv_heads, cfg.head_dim) == (D, L, HEADS, KV, HD)
    assert cfg.vocab_size == len(_vocab())
    assert cfg.intermediate_size == F
    assert not cfg.tie_word_embeddings  # output.weight present
    assert g.metadata["general.name"] == "tiny-gguf"
    g.close()


def test_tensor_types_roundtrip(tmp_path):
    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    rng = np.random.RandomState(0)
    toks = _vocab()
    embed = (rng.randn(len(toks), D) * 0.05).astype(np.float32)
    np.testing.assert_allclose(g.tensor("token_embd.weight"), embed,
                               rtol=0, atol=0)   # F32 exact
    # F16 within half precision
    got = g.tensor("output.weight")
    assert got.shape == (len(toks), D)
    # Q8_0 within 1% of scale
    q = g.tensor("blk.0.attn_output.weight")
    assert q.shape == (D, HEADS * HD)
    g.close()


def test_gguf_engine_generates(tmp_path):
    """Params loaded from GGUF drive the engine end to end."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams
    import dataclasses

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    cfg = dataclasses.replace(config_from_gguf(g), dtype="float32",
                              max_model_len=128)
    params = load_params_from_gguf(g, cfg)
    g.close()
    eng = NativeEngine(cfg, EngineConfig(
        page_size=8, num_pages=32, max_slots=2, max_prefill_chunk=16,
        prefill_buckets=(8, 16), max_model_len=128), params=params)
    out = eng.generate(list(range(5, 17)),
                       SamplingParams(max_tokens=4, ignore_eos=True), "g")
    assert len(out) == 4
    assert all(0 <= t < cfg.vocab_size for t in out)


def test_gguf_tokenizer(tmp_path):
    path = str(tmp_path / "m.gguf")
    toks = make_tiny_gguf(path)
    tok = GGUFTokenizer(GGUFFile(path))
    assert tok.vocab_size == len(toks)
    assert tok.eos_token_ids == [2]
    ids = tok.encode("hello world")
    assert toks.index("▁hello") in ids
    assert tok.decode(ids) == "hello world"
    # byte fallback for text outside the vocab
    ids2 = tok.encode("hello zebra!")
    assert tok.decode(ids2) == "hello zebra!"


def test_model_card_from_gguf(tmp_path):
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    card = ModelDeploymentCard.from_gguf(path)
    assert card.name == "tiny-gguf"
    assert card.eos_token_ids == [2]
    assert card.context_length == 256
    cfg = card.model_config()
    assert cfg.hidden_size == D
    t = card.load_tokenizer()
    assert t.decode(t.encode("the world")) == "the world"


def test_unsupported_quant_named(tmp_path):
    path = str(tmp_path / "q4.gguf")
    arr = np.zeros((2, 32), np.float32)
    write_gguf(path, {"general.architecture": (8, "llama")},
               {"w": (2, arr, b"\x00" * 40)})  # Q4_0
    g = GGUFFile(path)
    with pytest.raises(ValueError, match="Q4_0"):
        g.tensor("w")
    g.close()
