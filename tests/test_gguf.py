"""GGUF sourcing tests (VERDICT r2 next #10; reference lib/llm/src/gguf.rs).

A self-contained GGUF *writer* lives in the test so the parser is validated
against independently-generated files (container layout per the public GGUF
spec), covering: typed metadata (scalars, strings, arrays), F32/F16/Q8_0
tensors with alignment, config mapping, params loading into a generating
engine, the embedded tokenizer, and ModelDeploymentCard.from_gguf.
"""
import dataclasses
import os
import struct

import numpy as np
import pytest

from dynamo_tpu.llm.gguf import (
    GGUFFile, GGUFTokenizer, config_from_gguf, load_params_from_gguf,
)

ALIGN = 32


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<Q", len(b)) + b


def _pack_value(vtype: int, v) -> bytes:
    fmts = {0: "<B", 1: "<b", 2: "<H", 3: "<h", 4: "<I", 5: "<i", 6: "<f",
            7: "<?", 10: "<Q", 11: "<q", 12: "<d"}
    if vtype in fmts:
        return struct.pack(fmts[vtype], v)
    if vtype == 8:
        return _pack_str(v)
    raise ValueError(vtype)


def write_gguf(path, metadata, tensors):
    """metadata: {key: (vtype, value) | (9, (etype, [values]))};
    tensors: {name: (ggml_type, np_array_rowmajor, raw_bytes)}."""
    out = bytearray()
    out += b"GGUF" + struct.pack("<I", 3)
    out += struct.pack("<QQ", len(tensors), len(metadata))
    for key, (vtype, value) in metadata.items():
        out += _pack_str(key)
        out += struct.pack("<I", vtype)
        if vtype == 9:
            etype, values = value
            out += struct.pack("<I", etype) + struct.pack("<Q", len(values))
            for v in values:
                out += _pack_value(etype, v)
        else:
            out += _pack_value(vtype, value)
    offset = 0
    blobs = []
    for name, (gtype, arr, raw) in tensors.items():
        dims = list(reversed(arr.shape))  # ne order: fastest first
        out += _pack_str(name)
        out += struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, offset)
        blobs.append((offset, raw))
        offset += (len(raw) + ALIGN - 1) // ALIGN * ALIGN
    pad = (-len(out)) % ALIGN
    out += b"\x00" * pad
    data_start = len(out)
    out += b"\x00" * offset
    for off, raw in blobs:
        out[data_start + off:data_start + off + len(raw)] = raw
    with open(path, "wb") as f:
        f.write(out)


def _f32(arr):
    return (0, arr, np.ascontiguousarray(arr, np.float32).tobytes())


def _f16(arr):
    return (1, arr, np.ascontiguousarray(arr, np.float16).tobytes())


def _q8_0(arr):
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1, 32)
    scale = np.abs(flat).max(axis=1, keepdims=True) / 127.0
    scale[scale == 0] = 1.0
    qs = np.clip(np.round(flat / scale), -127, 127).astype(np.int8)
    raw = b"".join(
        scale[i].astype(np.float16).tobytes() + qs[i].tobytes()
        for i in range(flat.shape[0]))
    return (8, arr, raw)


D, HEADS, KV, HD, L, F = 32, 4, 2, 8, 2, 64


# SPM vocab with full merge chains: score-driven BPE (the faithful
# llama.cpp algorithm) builds tokens bottom-up from characters, so every
# intermediate piece must exist; scores encode the merge-rank priority
# (higher = merged earlier), chars/specials score 0
_SPM_MERGE_ORDER = ["he", "lo", "hel", "hello", "▁hello",
                    "wo", "wor", "worl", "world", "▁world",
                    "th", "the", "▁the"]


def _vocab():
    toks = ["<unk>", "<s>", "</s>"]
    toks += [f"<0x{b:02X}>" for b in range(256)]
    toks += list("▁helowrdt") + _SPM_MERGE_ORDER
    return toks


def _spm_scores(toks):
    return [float(-(_SPM_MERGE_ORDER.index(t) + 1))
            if t in _SPM_MERGE_ORDER else 0.0 for t in toks]


def make_tiny_gguf(path, embed_type=_f32):
    rng = np.random.RandomState(0)
    toks = _vocab()
    vocab = len(toks)

    def r(*shape):
        return (rng.randn(*shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": embed_type(r(vocab, D)),
        "output_norm.weight": _f32(np.ones(D, np.float32)),
        "output.weight": _f16(r(vocab, D)),
    }
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.attn_q.weight": _f32(r(HEADS * HD, D)),
            f"blk.{i}.attn_k.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_v.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_output.weight": _q8_0(r(D, HEADS * HD)),
            f"blk.{i}.ffn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.ffn_gate.weight": _f32(r(F, D)),
            f"blk.{i}.ffn_up.weight": _f32(r(F, D)),
            f"blk.{i}.ffn_down.weight": _f32(r(D, F)),
        })
    metadata = {
        "general.architecture": (8, "llama"),
        "general.name": (8, "tiny-gguf"),
        "llama.embedding_length": (4, D),
        "llama.block_count": (4, L),
        "llama.feed_forward_length": (4, F),
        "llama.attention.head_count": (4, HEADS),
        "llama.attention.head_count_kv": (4, KV),
        "llama.attention.layer_norm_rms_epsilon": (6, 1e-5),
        "llama.rope.freq_base": (6, 10000.0),
        "llama.context_length": (4, 256),
        "tokenizer.ggml.model": (8, "llama"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.scores": (9, (6, _spm_scores(toks))),
        "tokenizer.ggml.bos_token_id": (4, 1),
        "tokenizer.ggml.eos_token_id": (4, 2),
    }
    write_gguf(path, metadata, tensors)
    return toks


def test_parse_config_and_metadata(tmp_path):
    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    cfg = config_from_gguf(g)
    assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads,
            cfg.num_kv_heads, cfg.head_dim) == (D, L, HEADS, KV, HD)
    assert cfg.vocab_size == len(_vocab())
    assert cfg.intermediate_size == F
    assert not cfg.tie_word_embeddings  # output.weight present
    assert g.metadata["general.name"] == "tiny-gguf"
    g.close()


def test_gemma_gguf_config_flags(tmp_path):
    """gemma-arch ggufs map to the Gemma architecture deltas (sqrt(d)
    embed scale, (1+w) norms, tanh-GELU); tensor names are the same
    llama.cpp blk.N.* layout so loading is shared with llama."""
    path = str(tmp_path / "g.gguf")
    toks = _vocab()
    metadata = {
        "general.architecture": (8, "gemma"),
        "gemma.embedding_length": (4, 64),
        "gemma.block_count": (4, 1),
        "gemma.feed_forward_length": (4, 128),
        "gemma.attention.head_count": (4, 4),
        "gemma.attention.head_count_kv": (4, 1),
        "gemma.attention.key_length": (4, 32),
        "gemma.attention.layer_norm_rms_epsilon": (6, 1e-6),
        "gemma.context_length": (4, 256),
        "tokenizer.ggml.model": (8, "llama"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.scores": (9, (6, _spm_scores(toks))),
    }
    write_gguf(path, metadata, {"token_embd.weight": _f32(
        np.zeros((len(toks), 64), np.float32))})
    g = GGUFFile(path)
    cfg = config_from_gguf(g)
    g.close()
    assert cfg.norm_plus_one and cfg.mlp_act == "gelu_tanh"
    assert abs(cfg.embed_scale - 8.0) < 1e-9
    assert cfg.head_dim == 32 and cfg.num_kv_heads == 1
    assert cfg.tie_word_embeddings  # no output.weight -> tied


def test_gemma_gguf_logit_parity_with_hf(tmp_path):
    """A gemma GGUF written the way llama.cpp's converter writes it
    (norm weights stored WITH the baked +1) must produce the same logits
    as the safetensors checkpoint through transformers: catches the
    double-(1+w) bug class."""
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.llama import AttnMetadata
    import jax.numpy as jnp

    torch.manual_seed(0)
    hf = GemmaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, head_dim=8,
                     max_position_embeddings=64, rope_theta=10000.0)
    m = GemmaForCausalLM(hf)
    m.eval()
    sd = {k: v.float().numpy() for k, v in m.state_dict().items()}

    tensors = {
        # converter bakes +1 into every norm weight
        "token_embd.weight": _f32(sd["model.embed_tokens.weight"]),
        "output_norm.weight": _f32(sd["model.norm.weight"] + 1.0),
    }
    for i in range(2):
        p = f"model.layers.{i}."
        tensors.update({
            f"blk.{i}.attn_norm.weight": _f32(
                sd[p + "input_layernorm.weight"] + 1.0),
            f"blk.{i}.attn_q.weight": _f32(sd[p + "self_attn.q_proj.weight"]),
            f"blk.{i}.attn_k.weight": _f32(sd[p + "self_attn.k_proj.weight"]),
            f"blk.{i}.attn_v.weight": _f32(sd[p + "self_attn.v_proj.weight"]),
            f"blk.{i}.attn_output.weight": _f32(
                sd[p + "self_attn.o_proj.weight"]),
            f"blk.{i}.ffn_norm.weight": _f32(
                sd[p + "post_attention_layernorm.weight"] + 1.0),
            f"blk.{i}.ffn_gate.weight": _f32(sd[p + "mlp.gate_proj.weight"]),
            f"blk.{i}.ffn_up.weight": _f32(sd[p + "mlp.up_proj.weight"]),
            f"blk.{i}.ffn_down.weight": _f32(sd[p + "mlp.down_proj.weight"]),
        })
    toks = _vocab()
    metadata = {
        "general.architecture": (8, "gemma"),
        "gemma.embedding_length": (4, 32),
        "gemma.block_count": (4, 2),
        "gemma.feed_forward_length": (4, 64),
        "gemma.attention.head_count": (4, 4),
        "gemma.attention.head_count_kv": (4, 2),
        "gemma.attention.key_length": (4, 8),
        "gemma.attention.layer_norm_rms_epsilon": (6, hf.rms_norm_eps),
        "gemma.rope.freq_base": (6, 10000.0),
        "gemma.context_length": (4, 64),
        "gemma.vocab_size": (4, 64),
        "tokenizer.ggml.model": (8, "llama"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.scores": (9, (6, _spm_scores(toks))),
    }
    path = str(tmp_path / "gemma.gguf")
    write_gguf(path, metadata, tensors)
    g = GGUFFile(path)
    cfg = dataclasses.replace(config_from_gguf(g), dtype="float32")
    params = load_params_from_gguf(g, cfg)
    g.close()

    ids = np.arange(1, 9, dtype=np.int32)
    t = len(ids)
    cache = llama.init_cache(cfg, 2, 8)
    meta = AttnMetadata(
        positions=jnp.arange(t, dtype=jnp.int32)[None],
        page_table=jnp.arange(2, dtype=jnp.int32)[None],
        kv_lens=jnp.asarray([t], jnp.int32),
        write_idx=jnp.arange(t, dtype=jnp.int32)[None])
    ours, _ = llama.forward(params, cfg, jnp.asarray(ids)[None], cache, meta)
    with torch.no_grad():
        theirs = m(torch.tensor(ids[None].astype(np.int64))).logits[0].numpy()
    np.testing.assert_allclose(np.asarray(ours[0]), theirs,
                               rtol=2e-4, atol=2e-4)


def test_tensor_types_roundtrip(tmp_path):
    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    rng = np.random.RandomState(0)
    toks = _vocab()
    embed = (rng.randn(len(toks), D) * 0.05).astype(np.float32)
    np.testing.assert_allclose(g.tensor("token_embd.weight"), embed,
                               rtol=0, atol=0)   # F32 exact
    # F16 within half precision
    got = g.tensor("output.weight")
    assert got.shape == (len(toks), D)
    # Q8_0 within 1% of scale
    q = g.tensor("blk.0.attn_output.weight")
    assert q.shape == (D, HEADS * HD)
    g.close()


def test_gguf_engine_generates(tmp_path):
    """Params loaded from GGUF drive the engine end to end."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams
    import dataclasses

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    g = GGUFFile(path)
    cfg = dataclasses.replace(config_from_gguf(g), dtype="float32",
                              max_model_len=128)
    params = load_params_from_gguf(g, cfg)
    g.close()
    eng = NativeEngine(cfg, EngineConfig(
        page_size=8, num_pages=32, max_slots=2, max_prefill_chunk=16,
        prefill_buckets=(8, 16), max_model_len=128), params=params)
    out = eng.generate(list(range(5, 17)),
                       SamplingParams(max_tokens=4, ignore_eos=True), "g")
    assert len(out) == 4
    assert all(0 <= t < cfg.vocab_size for t in out)


def test_gguf_tokenizer(tmp_path):
    path = str(tmp_path / "m.gguf")
    toks = make_tiny_gguf(path)
    tok = GGUFTokenizer(GGUFFile(path))
    assert tok.vocab_size == len(toks)
    assert tok.eos_token_ids == [2]
    ids = tok.encode("hello world")
    assert toks.index("▁hello") in ids
    assert tok.decode(ids) == "hello world"
    # byte fallback for text outside the vocab
    ids2 = tok.encode("hello zebra!")
    assert tok.decode(ids2) == "hello zebra!"


def test_model_card_from_gguf(tmp_path):
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    card = ModelDeploymentCard.from_gguf(path)
    assert card.name == "tiny-gguf"
    assert card.eos_token_ids == [2]
    assert card.context_length == 256
    cfg = card.model_config()
    assert cfg.hidden_size == D
    t = card.load_tokenizer()
    assert t.decode(t.encode("the world")) == "the world"


def test_unsupported_quant_named(tmp_path):
    path = str(tmp_path / "q2.gguf")
    arr = np.zeros((1, 256), np.float32)
    write_gguf(path, {"general.architecture": (8, "llama")},
               {"w": (10, arr, b"\x00" * 84)})  # Q2_K
    g = GGUFFile(path)
    with pytest.raises(ValueError, match="Q2_K"):
        g.tensor("w")
    g.close()


def _ref_dequant_q4_0(raw: bytes, n: int) -> np.ndarray:
    """Scalar reference straight from the llama.cpp formulas (independent
    of the vectorized implementation under test)."""
    out = np.empty(n, np.float32)
    for b in range(n // 32):
        blk = raw[b * 18:(b + 1) * 18]
        d = np.frombuffer(blk[:2], np.float16)[0].astype(np.float32)
        qs = blk[2:]
        for i in range(16):
            out[b * 32 + i] = ((qs[i] & 0x0F) - 8) * d
            out[b * 32 + 16 + i] = ((qs[i] >> 4) - 8) * d
    return out


def _ref_dequant_q4_k(raw: bytes, n: int) -> np.ndarray:
    def scale_min(j, sc):
        if j < 4:
            return sc[j] & 63, sc[j + 4] & 63
        return ((sc[j + 4] & 0x0F) | ((sc[j - 4] >> 6) << 4),
                (sc[j + 4] >> 4) | ((sc[j] >> 6) << 4))

    out = np.empty(n, np.float32)
    for b in range(n // 256):
        blk = raw[b * 144:(b + 1) * 144]
        d = np.frombuffer(blk[0:2], np.float16)[0].astype(np.float32)
        dmin = np.frombuffer(blk[2:4], np.float16)[0].astype(np.float32)
        sc = blk[4:16]
        qs = blk[16:]
        y = b * 256
        for j64 in range(4):  # 64 values per strip
            s1, m1 = scale_min(2 * j64, sc)
            s2, m2 = scale_min(2 * j64 + 1, sc)
            q = qs[j64 * 32:(j64 + 1) * 32]
            for l in range(32):
                out[y + l] = d * s1 * (q[l] & 0x0F) - dmin * m1
                out[y + 32 + l] = d * s2 * (q[l] >> 4) - dmin * m2
            y += 64
    return out


def _ref_dequant_q6_k(raw: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.float32)
    for b in range(n // 256):
        blk = raw[b * 210:(b + 1) * 210]
        ql, qh = blk[:128], blk[128:192]
        sc = np.frombuffer(blk[192:208], np.int8)
        d = np.frombuffer(blk[208:210], np.float16)[0].astype(np.float32)
        y = b * 256
        for half in range(2):
            lo, h = ql[half * 64:half * 64 + 64], qh[half * 32:half * 32 + 32]
            s = sc[half * 8:half * 8 + 8]
            for l in range(32):
                i = l // 16
                q1 = ((lo[l] & 0x0F) | (((h[l] >> 0) & 3) << 4)) - 32
                q2 = ((lo[l + 32] & 0x0F) | (((h[l] >> 2) & 3) << 4)) - 32
                q3 = ((lo[l] >> 4) | (((h[l] >> 4) & 3) << 4)) - 32
                q4 = ((lo[l + 32] >> 4) | (((h[l] >> 6) & 3) << 4)) - 32
                out[y + l] = d * s[i] * q1
                out[y + 32 + l] = d * s[i + 2] * q2
                out[y + 64 + l] = d * s[i + 4] * q3
                out[y + 96 + l] = d * s[i + 6] * q4
            y += 128
    return out


@pytest.mark.parametrize("gtype,name,block_bytes,block_vals,ref", [
    (2, "Q4_0", 18, 32, _ref_dequant_q4_0),
    (12, "Q4_K", 144, 256, _ref_dequant_q4_k),
    (14, "Q6_K", 210, 256, _ref_dequant_q6_k),
])
def test_quant_dequant_matches_scalar_reference(tmp_path, gtype, name,
                                                block_bytes, block_vals,
                                                ref):
    """VERDICT r3 #5: Q4_0/Q4_K/Q6_K dequant — the vectorized loader must
    agree bit-for-bit with a scalar re-derivation of the llama.cpp block
    formulas on random block bytes."""
    rng = np.random.RandomState(7 + gtype)
    n = 2 * block_vals
    raw = rng.randint(0, 256, 2 * block_bytes, dtype=np.uint8)
    # keep the f16 scale fields finite (random bytes can encode NaN/inf)
    for base in range(0, len(raw), block_bytes):
        f16 = np.float16(rng.uniform(-2, 2))
        scale_off = base + (208 if gtype == 14 else 0)
        raw[scale_off:scale_off + 2] = np.frombuffer(
            f16.tobytes(), np.uint8)
        if gtype == 12:  # dmin
            raw[base + 2:base + 4] = np.frombuffer(
                np.float16(rng.uniform(0, 1)).tobytes(), np.uint8)
    path = str(tmp_path / "q.gguf")
    arr = np.zeros((2, block_vals), np.float32)
    write_gguf(path, {"general.architecture": (8, "llama")},
               {"w": (gtype, arr, raw.tobytes())})
    g = GGUFFile(path)
    got = g.tensor("w").reshape(-1)
    want = ref(raw.tobytes(), n)
    np.testing.assert_allclose(got, want, rtol=0, atol=0)
    g.close()


def _byte_level_vocab_and_merges():
    """A tiny byte-level BPE: the 256-char ByteLevel alphabet as base
    tokens plus a few merges (enough to check merge application and the
    Ġ space convention)."""
    from tokenizers import pre_tokenizers
    alphabet = sorted(pre_tokenizers.ByteLevel.alphabet())
    toks = list(alphabet)
    merges = [("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o"),
              ("Ġ", "w"), ("o", "r"), ("Ġw", "or"), ("l", "d"),
              ("Ġwor", "ld")]
    for a, b in merges:
        toks.append(a + b)
    return toks, [f"{a} {b}" for a, b in merges]


def test_gpt2_gguf_tokenizer_matches_hf(tmp_path):
    """ADVICE r3 medium + VERDICT r3 #5: a gpt2-model GGUF (llama-3/qwen2
    style byte-level BPE with Ġ markers, no <0xXX> tokens) must tokenize
    via real merges — byte-for-byte the ids an HF tokenizer built from
    the same vocab+merges produces — instead of degrading to
    unk-per-char on spaces."""
    from tokenizers import Regex, Tokenizer, decoders, models
    from tokenizers import pre_tokenizers as pt

    toks, merges = _byte_level_vocab_and_merges()
    special = "<|eot|>"
    toks.append(special)
    types = [1] * (len(toks) - 1) + [3]  # last token is control
    path = str(tmp_path / "bpe.gguf")
    write_gguf(path, {
        "general.architecture": (8, "llama"),
        "tokenizer.ggml.model": (8, "gpt2"),
        "tokenizer.ggml.pre": (8, "llama-bpe"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.merges": (9, (8, merges)),
        "tokenizer.ggml.token_type": (9, (5, types)),
        "tokenizer.ggml.eos_token_id": (4, len(toks) - 1),
    }, {})
    tok = GGUFTokenizer(GGUFFile(path))

    # independent HF construction from the same vocab+merges (the
    # reference's conversion target, gguf_tokenizer.rs:234)
    pat = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|"
           r"\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+")
    hf = Tokenizer(models.BPE(
        vocab={t: i for i, t in enumerate(toks)},
        merges=[tuple(m.split(" ", 1)) for m in merges],
        ignore_merges=True))
    hf.pre_tokenizer = pt.Sequence([
        pt.Split(Regex(pat), behavior="isolated"),
        pt.ByteLevel(add_prefix_space=False, use_regex=False)])
    hf.decoder = decoders.ByteLevel()

    for text in ("hello world", "hello   world!", "I'm 12345 ok",
                 "héllo wörld", "line\nbreak  x"):
        assert tok.encode(text) == hf.encode(text).ids, text
        assert tok.decode(tok.encode(text)) == text, text

    # spaces must ride Ġ merges, not unk-per-char (the ADVICE bug)
    ids = tok.encode("hello world")
    assert toks.index("hello") in ids
    assert toks.index("Ġworld") in ids

    # control tokens encode atomically
    ids2 = tok.encode(f"hello{special}")
    assert ids2[-1] == len(toks) - 1


def test_unknown_tokenizer_model_rejected(tmp_path):
    path = str(tmp_path / "wp.gguf")
    write_gguf(path, {
        "general.architecture": (8, "llama"),
        "tokenizer.ggml.model": (8, "bert"),
        "tokenizer.ggml.tokens": (9, (8, ["a", "b"])),
    }, {})
    with pytest.raises(ValueError, match="bert"):
        GGUFTokenizer(GGUFFile(path))


def test_config_from_gguf_names_missing_keys(tmp_path):
    path = str(tmp_path / "trunc.gguf")
    write_gguf(path, {
        "general.architecture": (8, "llama"),
        "llama.embedding_length": (4, 32),
    }, {})
    g = GGUFFile(path)
    with pytest.raises(ValueError, match="llama.attention.head_count"):
        config_from_gguf(g)
    g.close()


def test_run_launcher_serves_gguf_file_with_quant(tmp_path):
    """`python -m dynamo_tpu.run in=stdin out=native model.gguf --quant
    int8`: the single-file GGUF flow the reference's dynamo-run offers
    (opt.rs GGUF detection), through the full launcher — card from the
    file's metadata, streamed int8 quantization at load, one completion
    out."""
    import subprocess
    import sys

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run", "in=stdin", "out=native",
         path, "--quant", "int8", "--num-pages", "32", "--max-slots", "2",
         "--max-tokens", "8"],
        input="hello there", capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"},
        cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    # random tiny weights: any decoded text proves the full path ran
    assert out.stdout.strip() != ""


def make_tiny_moe_gguf(path, e=4):
    """Mixtral-class gguf: fused expert tensors + routing gate."""
    rng = np.random.RandomState(1)
    toks = _vocab()
    vocab = len(toks)

    def r(*shape):
        return (rng.randn(*shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": _f32(r(vocab, D)),
        "output_norm.weight": _f32(np.ones(D, np.float32)),
        "output.weight": _f32(r(vocab, D)),
    }
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.attn_q.weight": _f32(r(HEADS * HD, D)),
            f"blk.{i}.attn_k.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_v.weight": _f32(r(KV * HD, D)),
            f"blk.{i}.attn_output.weight": _f32(r(D, HEADS * HD)),
            f"blk.{i}.ffn_norm.weight": _f32(np.ones(D, np.float32)),
            f"blk.{i}.ffn_gate_inp.weight": _f32(r(e, D)),
            f"blk.{i}.ffn_gate_exps.weight": _f32(r(e, F, D)),
            f"blk.{i}.ffn_up_exps.weight": _f32(r(e, F, D)),
            f"blk.{i}.ffn_down_exps.weight": _f32(r(e, D, F)),
        })
    metadata = {
        "general.architecture": (8, "llama"),
        "general.name": (8, "tiny-moe-gguf"),
        "llama.embedding_length": (4, D),
        "llama.block_count": (4, L),
        "llama.feed_forward_length": (4, F),
        "llama.attention.head_count": (4, HEADS),
        "llama.attention.head_count_kv": (4, KV),
        "llama.attention.layer_norm_rms_epsilon": (6, 1e-5),
        "llama.rope.freq_base": (6, 10000.0),
        "llama.context_length": (4, 256),
        "llama.expert_count": (4, e),
        "llama.expert_used_count": (4, 2),
        "tokenizer.ggml.model": (8, "llama"),
        "tokenizer.ggml.tokens": (9, (8, toks)),
        "tokenizer.ggml.scores": (9, (6, _spm_scores(toks))),
        "tokenizer.ggml.bos_token_id": (4, 1),
        "tokenizer.ggml.eos_token_id": (4, 2),
    }
    write_gguf(path, metadata, tensors)


def test_moe_gguf_config_load_and_generate(tmp_path):
    """Mixtral-class gguf sourcing: expert_count metadata -> MoE config,
    fused blk.N.ffn_*_exps tensors -> our stacked expert layout (exact
    per-expert transpose), routing gate -> router, and the loaded params
    drive a generating engine."""
    import dataclasses

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    path = str(tmp_path / "moe.gguf")
    make_tiny_moe_gguf(path)
    g = GGUFFile(path)
    cfg = config_from_gguf(g)
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
    cfg = dataclasses.replace(cfg, dtype="float32", max_model_len=128)
    params = load_params_from_gguf(g, cfg)
    # exact layout mapping: [E, out, in] file tensors -> [E, in, out] ours
    for i in range(L):
        np.testing.assert_array_equal(
            params["layers"]["w_gate"][i],
            np.swapaxes(g.tensor(f"blk.{i}.ffn_gate_exps.weight"), 1, 2))
        np.testing.assert_array_equal(
            params["layers"]["w_down"][i],
            np.swapaxes(g.tensor(f"blk.{i}.ffn_down_exps.weight"), 1, 2))
        np.testing.assert_array_equal(
            params["layers"]["router"][i],
            g.tensor(f"blk.{i}.ffn_gate_inp.weight").T)
    g.close()

    eng = NativeEngine(cfg, EngineConfig(
        page_size=8, num_pages=32, max_slots=2, max_prefill_chunk=16,
        prefill_buckets=(8, 16), max_model_len=128), params=params)
    out = eng.generate(list(range(5, 17)),
                       SamplingParams(max_tokens=4, ignore_eos=True), "m")
    assert len(out) == 4
    assert all(0 <= t < cfg.vocab_size for t in out)


def test_dense_gguf_with_missing_expert_tensors_errors_clearly(tmp_path):
    """An MoE config whose gguf lacks the fused expert tensors must name
    the problem, not KeyError deep in a stack() loop."""
    import dataclasses

    path = str(tmp_path / "m.gguf")
    make_tiny_gguf(path)  # dense tensors only
    g = GGUFFile(path)
    cfg = dataclasses.replace(config_from_gguf(g), num_experts=4)
    with pytest.raises(ValueError, match="fused expert tensors"):
        load_params_from_gguf(g, cfg)
    g.close()
