"""Native-engine worker tests: async serving loop, KV events, routing, abort."""
import asyncio

import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane

CFG = ModelConfig(dtype="float32", max_model_len=512)
PAGE = 8


def make_engine():
    return NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=64, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)


def pre_request(rid, prompt, max_tokens=6):
    return PreprocessedRequest(
        request_id=rid, token_ids=prompt,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).model_dump(exclude_none=True)


def test_worker_streams_match_direct_engine():
    prompt = list(range(10, 30))
    direct = make_engine().generate(prompt, SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True), "d")

    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w1")
        worker = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt, "ns", "backend", worker)

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        toks = []
        async for frame in await client.generate(pre_request("r1", prompt)):
            toks.extend(frame.get("token_ids", ()))
        await worker.stop()
        await crt.shutdown()
        await wrt.shutdown()
        return toks

    assert asyncio.run(main()) == direct


def test_worker_concurrent_requests_and_metrics():
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w1")
        worker = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt, "ns", "backend", worker)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        async def one(rid, base):
            prompt = list(range(base, base + 12))
            toks = []
            async for frame in await client.generate(pre_request(rid, prompt)):
                toks.extend(frame.get("token_ids", ()))
            return toks

        results = await asyncio.gather(one("a", 5), one("b", 50), one("c", 100))
        assert all(len(r) == 6 for r in results)
        stats = await client.scrape_stats()
        assert stats["w1"]["request_total_slots"] == 4
        assert stats["w1"]["kv_total_blocks"] == 64
        await worker.stop()
        await crt.shutdown()
        await wrt.shutdown()

    asyncio.run(main())


def test_worker_kv_events_feed_router():
    """Worker publishes page events; the router learns which worker holds
    the prefix and routes a matching request there (SURVEY.md §3.4 path)."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "warm")
        comp = wrt.namespace("ns").component("backend")
        worker = await NativeEngineWorker(
            make_engine(), component=comp, worker_id="warm").start()
        await serve_llm_worker(wrt, "ns", "backend", worker)

        # a second cold worker with no cached pages
        wrt2 = await DistributedRuntime.create_local(plane, "cold")
        worker2 = await NativeEngineWorker(
            make_engine(), component=wrt2.namespace("ns").component("backend"),
            worker_id="cold").start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2)

        rrt = await DistributedRuntime.create_local(plane, "router")
        rcomp = rrt.namespace("ns").component("backend")
        client = rcomp.endpoint("generate").client()
        await client.start()
        await client.wait_for_instances()
        router = await KvRouter(rcomp, client, block_size=PAGE,
                                scrape_interval_s=0.05).start()

        prompt = list(range(200, 232))  # 32 tokens = 4 full pages
        async for _ in await client.direct(pre_request("warmup", prompt),
                                           "warm"):
            pass
        await asyncio.sleep(0.3)  # event + metrics propagation

        scores = router.find_matches_for_tokens(prompt).scores
        assert scores.get("warm", 0) >= 3, scores
        assert "cold" not in scores
        # KV-aware choice sends the matching prompt back to the warm worker
        assert await router.schedule(prompt) == "warm"

        await router.stop()
        await worker.stop()
        await worker2.stop()
        for rt in (rrt, wrt, wrt2):
            await rt.shutdown()

    asyncio.run(main())


def test_client_stop_aborts_engine_request():
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w1")
        engine = make_engine()
        worker = await NativeEngineWorker(engine).start()
        await serve_llm_worker(wrt, "ns", "backend", worker)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        ctx = Context()
        prompt = list(range(10, 26))
        count = 0
        async for frame in await client.generate(
                pre_request("r1", prompt, max_tokens=200), ctx):
            count += frame and 1
            if count == 3:
                ctx.stop_generating()
        # engine slot freed (abort reached the worker). Aborts apply between
        # device steps; a cold-jit recompile of a decode window can hold one
        # step for many seconds on CPU, so poll with a deadline rather than
        # a fixed sleep.
        for _ in range(240):
            m = engine.metrics()
            if m.request_active_slots == 0:
                break
            await asyncio.sleep(0.25)
        assert m.request_active_slots == 0
        assert m.num_requests_waiting == 0
        await worker.stop()
        await crt.shutdown()
        await wrt.shutdown()

    asyncio.run(main())


def test_sigterm_graceful_drain(tmp_path):
    """k8s rolling-restart behavior (install_graceful_drain): SIGTERM to a
    serving worker deregisters it immediately (no new routing) but lets
    the in-flight stream FINISH before the process exits cleanly — the
    reference's runtime-cancellation-token graceful shutdown."""
    import os
    import signal
    import subprocess
    import sys

    from dynamo_tpu.runtime.transports.server import ControlPlaneServer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    async def main():
        server = await ControlPlaneServer(port=0).start()
        env = {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.run",
             "in=endpoint:ns.echo.generate", "out=echo", "tiny",
             "--echo-delay", "0.1", "--control-port", str(server.port)],
            stdout=subprocess.PIPE, text=True, cwd=repo, env=env)
        try:
            # readline must not block the loop: the control plane serving
            # the worker's connect runs IN this loop
            line = await asyncio.get_running_loop().run_in_executor(
                None, proc.stdout.readline)
            assert "READY" in line, line
            rt = await DistributedRuntime.connect(
                "127.0.0.1", server.port, "cl")
            client = rt.namespace("ns").component("echo").endpoint(
                "generate").client()
            await client.start()
            await client.wait_for_instances()
            req = {"request_id": "g1", "token_ids": list(range(30)),
                   "stop": {"max_tokens": 30}}
            frames = []
            stream = await client.generate(req)
            async for frame in stream:
                frames.append(frame)
                if len(frames) == 3:
                    proc.send_signal(signal.SIGTERM)  # mid-stream
            # the in-flight stream completed despite the SIGTERM
            toks = [t for f in frames for t in f.get("token_ids", ())]
            assert toks == list(range(30)), toks
            assert frames[-1].get("finish_reason") == "length"
            # worker exited cleanly after the drain (wait in an executor:
            # the worker's shutdown RPCs need this loop's control plane)
            rc = await asyncio.get_running_loop().run_in_executor(
                None, proc.wait, 30)
            assert rc == 0
            # and its instance was deregistered
            await asyncio.sleep(0.2)
            assert await rt.kv.get_prefix("ns/") == [] or all(
                "echo" not in e.key for e in
                await rt.kv.get_prefix("ns/components/"))
            await rt.shutdown()
        finally:
            if proc.poll() is None:
                proc.kill()
            await server.stop()

    asyncio.run(main())


def test_serve_llm_worker_attaches_event_publisher():
    """A NativeEngineWorker built WITHOUT a component (run.py endpoint
    mode, the SDK example workers — the engine exists before the runtime
    does) must still feed the KV event plane once served: serve_llm_worker
    attaches a publisher under the runtime's worker id. Without this a
    kv-routed frontend gets zero overlap data from launcher-started
    workers and silently degrades to load balancing (caught by
    tools/routing_ttft_bench.py)."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "launcher-w")
        worker = await NativeEngineWorker(make_engine()).start()
        assert worker.event_publisher is None
        await serve_llm_worker(wrt, "ns", "backend", worker)
        assert worker.event_publisher is not None

        crt = await DistributedRuntime.create_local(plane, "cl")
        sub = await crt.namespace("ns").component("backend").subscribe(
            "kv_events")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        prompt = list(range(100, 132))  # 4 full pages
        async for _ in await client.generate(pre_request("ev1", prompt)):
            pass

        async def first_event():
            async for _subj, payload in sub:
                return payload

        ev = await asyncio.wait_for(first_event(), 10)
        # the event stream must carry the id routers see in the instance
        # table, and the stored pages of the prompt
        assert ev["worker_id"] == "launcher-w"
        assert ev["data"]["kind"] == "stored"
        assert len(ev["data"]["blocks"]) >= 1
        await worker.stop()
        await crt.shutdown()
        await wrt.shutdown()

    asyncio.run(main())


def test_serve_llm_worker_publishes_serving_role():
    """ISSUE 12: an engine that self-describes a serving role
    (DisaggDecodeWorker.serving_role = "decode") lands it on the
    instance key, so role-filtered routing and the rollup's per-role
    aggregates see a real disagg fleet's split; explicit role= wins,
    and plain engines stay role-less wildcards."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w-auto")
        worker = await NativeEngineWorker(make_engine()).start()
        worker.serving_role = "decode"      # what DisaggDecodeWorker sets
        await serve_llm_worker(wrt, "ns", "backend", worker)
        wrt2 = await DistributedRuntime.create_local(plane, "w-explicit")
        worker2 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt2, "ns", "backend", worker2,
                               role="prefill")
        wrt3 = await DistributedRuntime.create_local(plane, "w-plain")
        worker3 = await NativeEngineWorker(make_engine()).start()
        await serve_llm_worker(wrt3, "ns", "backend", worker3)

        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("backend").endpoint(
            "generate").client()
        await client.start()
        deadline = asyncio.get_running_loop().time() + 5.0
        while len(client.instances) < 3:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.01)
        decode = client.ids_for_role("decode")
        prefill = client.ids_for_role("prefill")
        assert "w-auto" in decode and "w-auto" not in prefill
        assert "w-explicit" in prefill and "w-explicit" not in decode
        # the role-less worker serves every role
        assert "w-plain" in decode and "w-plain" in prefill
        for rt in (crt, wrt, wrt2, wrt3):
            await rt.shutdown()

    asyncio.run(main())
