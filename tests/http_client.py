"""Tiny asyncio HTTP/1.1 test client (unary + SSE streaming)."""
from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator, Dict, Optional, Tuple


async def _read_headers(reader) -> Tuple[int, Dict[str, str]]:
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
    return status, headers


def _request_bytes(method: str, path: str, host: str,
                   body: Optional[bytes]) -> bytes:
    head = (f"{method} {path} HTTP/1.1\r\nhost: {host}\r\n"
            "content-type: application/json\r\n"
            f"content-length: {len(body or b'')}\r\n\r\n")
    return head.encode() + (body or b"")


async def request(host: str, port: int, method: str, path: str,
                  body=None, return_headers: bool = False):
    """Unary request; returns (status, full body bytes) — or
    (status, body, headers) with return_headers=True."""
    if isinstance(body, (dict, list)):
        body = json.dumps(body).encode()
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(_request_bytes(method, path, host, body))
        await writer.drain()
        status, headers = await _read_headers(reader)
        if headers.get("transfer-encoding") == "chunked":
            out = b""
            while True:
                size_line = await reader.readuntil(b"\r\n")
                size = int(size_line.strip(), 16)
                if size == 0:
                    await reader.readuntil(b"\r\n")
                    break
                out += await reader.readexactly(size)
                await reader.readexactly(2)
            return (status, out, headers) if return_headers \
                else (status, out)
        length = int(headers.get("content-length", "0"))
        out = await reader.readexactly(length) if length else b""
        return (status, out, headers) if return_headers else (status, out)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


async def sse_events(host: str, port: int, path: str, body,
                     max_events: Optional[int] = None
                     ) -> AsyncIterator[Tuple[Optional[str], str]]:
    """POST and yield (event, data) SSE tuples as they arrive; closing the
    generator drops the connection (client disconnect)."""
    if isinstance(body, (dict, list)):
        body = json.dumps(body).encode()
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(_request_bytes("POST", path, host, body))
        await writer.drain()
        status, headers = await _read_headers(reader)
        assert status == 200, status
        buf = b""
        n = 0
        while True:
            size_line = await reader.readuntil(b"\r\n")
            size = int(size_line.strip(), 16)
            if size == 0:
                break
            buf += await reader.readexactly(size)
            await reader.readexactly(2)
            while b"\n\n" in buf:
                block, buf = buf.split(b"\n\n", 1)
                event, datas = None, []
                for line in block.decode().split("\n"):
                    if line.startswith("event:"):
                        event = line[6:].strip()
                    elif line.startswith("data:"):
                        datas.append(line[5:].lstrip(" "))
                if datas or event:
                    yield event, "\n".join(datas)
                    n += 1
                    if max_events is not None and n >= max_events:
                        return
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
