"""Distributed runtime tests: component model, discovery, leases, streaming.

Modeled on the reference's runtime test strategy (SURVEY.md §4.2): closure
engines + in-memory control plane for most tests; a real TCP control-plane
server for the transport-integration tests (the analogue of the reference's
gated etcd/NATS tests, but self-contained so they always run).
"""
import asyncio

import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import MemoryPlane
from dynamo_tpu.runtime.transports.server import ControlPlaneServer


def run(coro):
    return asyncio.run(coro)


async def echo_engine(request, context):
    for i in range(int(request.get("n", 3))):
        if context.is_stopped:
            return
        yield {"i": i, "text": request.get("text", "")}


def test_serve_and_generate_memory_plane():
    async def main():
        plane = MemoryPlane()
        server_rt = await DistributedRuntime.create_local(plane, "worker1")
        client_rt = await DistributedRuntime.create_local(plane, "client1")
        ep = server_rt.namespace("ns").component("echo").endpoint("generate")
        await ep.serve(echo_engine)

        client = client_rt.namespace("ns").component("echo").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()
        frames = []
        async for frame in await client.generate({"n": 4, "text": "hi"}):
            frames.append(frame)
        assert [f["i"] for f in frames] == [0, 1, 2, 3]
        assert frames[0]["text"] == "hi"
        await client_rt.shutdown()
        await server_rt.shutdown()

    run(main())


def test_routing_policies_and_direct():
    async def main():
        plane = MemoryPlane()
        rts = []
        for wid in ("w1", "w2"):
            rt = await DistributedRuntime.create_local(plane, wid)
            ep = rt.namespace("ns").component("c").endpoint("gen")

            async def engine(request, context, wid=wid):
                yield {"worker": wid}

            await ep.serve(engine)
            rts.append(rt)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        await client.wait_for_instances()
        assert client.instance_ids() == ["w1", "w2"]

        # direct routing hits the requested instance
        for wid in ("w1", "w2"):
            frames = [f async for f in await client.direct({}, wid)]
            assert frames == [{"worker": wid}]

        # round robin alternates
        seen = []
        for _ in range(4):
            frames = [f async for f in await client.round_robin({})]
            seen.append(frames[0]["worker"])
        assert set(seen) == {"w1", "w2"}
        for rt in rts + [crt]:
            await rt.shutdown()

    run(main())


def test_instance_removed_on_shutdown():
    async def main():
        plane = MemoryPlane()
        rt1 = await DistributedRuntime.create_local(plane, "w1")
        ep = rt1.namespace("ns").component("c").endpoint("gen")
        await ep.serve(echo_engine)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        await client.wait_for_instances()
        assert client.instance_ids() == ["w1"]
        await rt1.shutdown()
        await asyncio.sleep(0.05)  # watch event propagation
        assert client.instance_ids() == []
        await crt.shutdown()

    run(main())


def test_client_watch_stream_death_recovers_and_converges():
    """Satellite regression: a killed watch stream must not leave a
    SILENT dead watcher. The pump resumes with backoff + jitter and
    resyncs from a full snapshot — registrations AND deregistrations
    that happened during the gap converge."""
    from dynamo_tpu.runtime import faults
    from dynamo_tpu.runtime.cpstats import CP_STATS

    async def main():
        plane = MemoryPlane()
        rt1 = await DistributedRuntime.create_local(plane, "w1")
        await rt1.namespace("ns").component("c").endpoint("gen").serve(
            echo_engine)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        await client.wait_for_instances()
        resyncs_before = CP_STATS.watch_resyncs

        # kill the next watch delivery: the stream raises into the pump
        faults.REGISTRY.arm("watch.stream", faults.FaultSchedule(
            0, [faults.FaultSpec("fail_n", n=1)]))
        # both events die WITH the stream; only the resync can recover them
        rt2 = await DistributedRuntime.create_local(plane, "w2")
        await rt2.namespace("ns").component("c").endpoint("gen").serve(
            echo_engine)
        await rt1.shutdown()   # w1 deregisters during the gap

        deadline = asyncio.get_running_loop().time() + 10
        while client.instance_ids() != ["w2"]:
            assert asyncio.get_running_loop().time() < deadline, \
                client.instances
            await asyncio.sleep(0.05)
        assert CP_STATS.watch_resyncs > resyncs_before
        faults.REGISTRY.disarm()

        # the resumed watcher is LIVE, not just resynced: later events
        # flow again without further faults
        await rt2.shutdown()
        deadline = asyncio.get_running_loop().time() + 5
        while client.instance_ids():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.05)
        await crt.shutdown()

    try:
        run(asyncio.wait_for(main(), 60))
    finally:
        from dynamo_tpu.runtime import faults
        faults.REGISTRY.disarm()
        faults.REGISTRY.reset_counters()


def test_client_watch_batch_coalesces_flaps():
    """A churn tick's events coalesce per key: N put/delete flaps on one
    key apply as ONE final state (and the coalesce counter advances)."""
    from dynamo_tpu.runtime.cpstats import CP_STATS

    async def main():
        plane = MemoryPlane()
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        seen = []
        client.add_listener(lambda kind, wid, info: seen.append((kind, wid)))
        CP_STATS.reset()
        # burst of flaps on one key, queued BEFORE the pump can tick:
        # the batch must fold to the final put
        key = "ns/components/c/gen:wf"
        import json as _json
        for i in range(9):
            await plane.kv.put(key, _json.dumps({"i": i}).encode())
        deadline = asyncio.get_running_loop().time() + 5
        while "wf" not in client.instances:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        assert client.instances["wf"]["i"] == 8   # final state won
        # fewer listener fires than raw events — the batching coalesced
        assert len([s for s in seen if s[1] == "wf"]) < 9
        assert CP_STATS.watch_events_coalesced > 0
        await crt.shutdown()

    run(asyncio.wait_for(main(), 30))


def test_lease_expiry_prunes_instances():
    """Killing keep-alive (by revoking through expiry path) removes keys —
    the reference's lease-TTL failure-detection behavior."""
    async def main():
        plane = MemoryPlane()
        lease = await plane.kv.grant_lease(ttl=0.15)
        await plane.kv.put("ns/components/c/gen:wX", b"{}", lease.id)
        assert await plane.kv.get("ns/components/c/gen:wX") is not None
        await asyncio.sleep(0.4)  # no keep-alive -> expiry
        assert await plane.kv.get("ns/components/c/gen:wX") is None
        assert lease.lost.is_set()

    run(main())


def test_cancellation_stops_stream():
    async def main():
        plane = MemoryPlane()
        srt = await DistributedRuntime.create_local(plane, "w")
        produced = []

        async def slow_engine(request, context):
            for i in range(1000):
                if context.is_stopped:
                    return
                produced.append(i)
                yield {"i": i}
                await asyncio.sleep(0.01)

        await srt.namespace("ns").component("c").endpoint("gen").serve(slow_engine)
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        ctx = Context()
        count = 0
        async for _ in await client.generate({"n": 1000}, ctx):
            count += 1
            if count == 5:
                ctx.stop_generating()
        await asyncio.sleep(0.2)
        assert count >= 5
        assert len(produced) < 1000  # engine observed the stop
        await crt.shutdown()
        await srt.shutdown()

    run(main())


def test_events_pub_sub():
    async def main():
        plane = MemoryPlane()
        rt = await DistributedRuntime.create_local(plane, "w")
        ns = rt.namespace("ns")
        sub = await ns.subscribe("kv_events")
        await ns.publish("kv_events", {"event_id": 1, "op": "stored"})
        subject, payload = await asyncio.wait_for(anext(sub), 1.0)
        assert subject == "ns.kv_events"
        assert payload["event_id"] == 1
        await rt.shutdown()

    run(main())


def test_stats_scrape():
    async def main():
        plane = MemoryPlane()
        rt = await DistributedRuntime.create_local(plane, "w1")
        ep = rt.namespace("ns").component("c").endpoint("gen")
        await ep.serve(echo_engine, stats_handler=lambda: {"load": 0.5})
        crt = await DistributedRuntime.create_local(plane, "cl")
        client = crt.namespace("ns").component("c").endpoint("gen").client()
        await client.start()
        await client.wait_for_instances()
        stats = await client.scrape_stats()
        assert stats == {"w1": {"load": 0.5}}
        await crt.shutdown()
        await rt.shutdown()

    run(main())


def test_keepalive_survives_slow_first_token(monkeypatch):
    """A responder whose first item takes longer than the requester's
    inactivity timeout must NOT be killed: keepalive frames prove liveness
    (VERDICT r2 weak #8)."""
    from dynamo_tpu.runtime import dataplane

    monkeypatch.setattr(dataplane, "KEEPALIVE_INTERVAL_S", 0.05)

    async def main():
        server = await dataplane.DataPlaneServer().start()
        stream = server.register()
        ctx = Context()

        async def slow_gen():
            await asyncio.sleep(0.5)  # >> per-frame timeout below
            yield b"tok"

        _, writer = await dataplane.call_home(
            server.connection_info, stream.stream_id, ctx)
        pump = asyncio.create_task(
            dataplane.pump_stream(writer, slow_gen(), ctx))
        # per-frame timeout far below the engine delay: only keepalives
        # keep this stream alive
        frames = [f async for f in server.stream_responses(
            stream, timeout=0.2)]
        assert frames == [b"tok"]
        await pump
        await server.stop()

    run(main())


def test_inactivity_raises_typed_error():
    """A responder that never connects (dead peer) surfaces as
    StreamInactiveError, not a bare timeout."""
    from dynamo_tpu.runtime import dataplane

    async def main():
        server = await dataplane.DataPlaneServer().start()
        stream = server.register()
        with pytest.raises(dataplane.StreamInactiveError):
            async for _ in server.stream_responses(stream, timeout=0.1):
                pass
        await server.stop()

    run(main())


# -- TCP control plane (integration, self-contained) --------------------------

def test_control_plane_durability(tmp_path):
    """ADVICE r2: control-plane state must survive a server death. Unleased
    KV (model registry, config) and work-queue contents (the JetStream-like
    prefill queue) are journaled and recovered; lease-scoped discovery keys
    are deliberately ephemeral (etcd semantics: leases die with the server,
    workers re-register on reconnect)."""
    data_dir = str(tmp_path / "cp")

    async def phase1():
        server = await ControlPlaneServer(port=0, data_dir=data_dir).start()
        try:
            rt = await DistributedRuntime.connect("127.0.0.1", server.port, "w")
            await rt.kv.put("models/m1", b"card1")
            await rt.kv.put("models/m2", b"card2")
            await rt.kv.delete("models/m2")
            lease = await rt.kv.grant_lease(10.0)
            await rt.kv.put("instances/w", b"ephemeral", lease.id)
            for i in range(3):
                await rt.messaging.queue_push("prefill", f"job{i}".encode())
            assert await rt.messaging.queue_pop("prefill", 1.0) == b"job0"
            await rt.shutdown()
        finally:
            await server.stop()

    async def phase2():
        server = await ControlPlaneServer(port=0, data_dir=data_dir).start()
        try:
            rt = await DistributedRuntime.connect("127.0.0.1", server.port, "w")
            assert await rt.kv.get("models/m1") == b"card1"
            assert await rt.kv.get("models/m2") is None
            assert await rt.kv.get("instances/w") is None  # lease-scoped
            assert await rt.messaging.queue_depth("prefill") == 2
            assert await rt.messaging.queue_pop("prefill", 1.0) == b"job1"
            await rt.shutdown()
        finally:
            await server.stop()

    run(phase1())
    run(phase2())


def test_queue_push_survives_sigkill(tmp_path):
    """VERDICT r3 #4: an ACKNOWLEDGED queue_push survives SIGKILL of the
    server process. The journal group-commits with fsync and the server
    acks a push only after its record reached stable storage (JetStream
    file-store semantics, SURVEY §L0) — so recovery must hold every item
    whose push returned, with at most the single in-flight unacked item
    beyond that."""
    import os
    import signal
    import subprocess
    import sys

    data_dir = str(tmp_path / "cp")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.runtime.transports.server",
         "--host", "127.0.0.1", "--port", "0", "--data-dir", data_dir],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": repo})
    acked = []
    try:
        port = None
        for line in proc.stdout:
            if line.startswith("READY"):
                port = int(line.strip().rsplit(":", 1)[1])
                break
        assert port, "server never printed READY"

        async def push_then_kill():
            rt = await DistributedRuntime.connect("127.0.0.1", port, "w")
            try:
                for i in range(20):
                    await rt.messaging.queue_push("prefill",
                                                  f"job{i}".encode())
                    acked.append(i)
                    if i == 13:
                        # SIGKILL immediately after an ack, no grace: the
                        # acknowledged records must already be on disk
                        proc.send_signal(signal.SIGKILL)
                        return
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                pass  # server died mid-push: only acked items count
            finally:
                # close the runtime INSIDE this loop: transports/tasks
                # abandoned at asyncio.run teardown are finalized by GC
                # later — potentially during the NEXT test's loop, where
                # a transport __del__ can close a since-reused fd (seen
                # as a 30s+60s hang in whatever test follows)
                try:
                    await asyncio.wait_for(rt.shutdown(), 5)
                except Exception:
                    pass

        run(push_then_kill())
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    assert len(acked) >= 1, "no push was ever acknowledged"
    from dynamo_tpu.runtime.transports.journal import DurablePlane
    plane = DurablePlane(data_dir)
    try:
        q = plane.messaging._queues["prefill"]
        items = list(q._queue)
        # every acknowledged push recovered, in order; at most one extra
        # in-flight (written-but-unacked) item may trail
        expect = [f"job{i}".encode() for i in acked]
        assert items[:len(expect)] == expect, (items, expect)
        assert len(items) <= len(expect) + 1, (items, expect)
    finally:
        plane.close()


def test_journal_compaction(tmp_path):
    """Snapshot compaction truncates the journal but preserves state."""
    from dynamo_tpu.runtime.transports.journal import DurablePlane

    async def main():
        plane = DurablePlane(str(tmp_path), compact_every=5)
        for i in range(12):  # crosses two compactions
            await plane.kv.put(f"k{i}", f"v{i}".encode())
        await plane.messaging.queue_push("q", b"x")
        plane.close()

        plane2 = DurablePlane(str(tmp_path))
        for i in range(12):
            assert await plane2.kv.get(f"k{i}") == f"v{i}".encode()
        assert await plane2.messaging.queue_depth("q") == 1
        plane2.close()

    run(main())


def test_compaction_crash_window_no_queue_duplication(tmp_path):
    """A crash between the snapshot rename and the journal truncation must
    not replay pre-compaction records onto the new snapshot (queue replay
    is not idempotent): the stale journal's generation header mismatches
    the snapshot and it is discarded (code-review r3)."""
    import shutil

    from dynamo_tpu.runtime.transports.journal import DurablePlane

    async def main():
        d = str(tmp_path)
        plane = DurablePlane(d, compact_every=1000)
        for item in (b"a", b"b", b"c"):
            await plane.messaging.queue_push("q", item)
        assert await plane.messaging.queue_pop("q", 1.0) == b"a"
        plane.journal.sync()  # flush-behind writer: settle before copying
        saved = d + "/journal.precompact"
        shutil.copy(plane.journal.journal_path, saved)
        plane.journal.compact()
        plane.journal.sync()
        # simulate the crash: the pre-compaction journal survives on disk
        shutil.copy(saved, plane.journal.journal_path)
        plane.close()

        plane2 = DurablePlane(d)
        assert await plane2.messaging.queue_depth("q") == 2
        assert await plane2.messaging.queue_pop("q", 1.0) == b"b"
        plane2.close()

    run(main())


def test_leased_put_shadowing_unleased_key_not_resurrected(tmp_path):
    """Overwriting a journaled unleased key with a lease-scoped value kills
    the old value for good — it must not resurrect on restart
    (code-review r3)."""
    from dynamo_tpu.runtime.transports.journal import DurablePlane

    async def main():
        d = str(tmp_path)
        plane = DurablePlane(d)
        await plane.kv.put("k", b"v1")
        lease = await plane.kv.grant_lease(10.0)
        await plane.kv.put("k", b"ephemeral", lease.id)
        plane.close()

        plane2 = DurablePlane(d)
        assert await plane2.kv.get("k") is None
        plane2.close()

    run(main())


def test_tcp_control_plane_end_to_end():
    async def main():
        server = await ControlPlaneServer(port=0).start()
        try:
            rt1 = await DistributedRuntime.connect("127.0.0.1", server.port, "w1")
            rt2 = await DistributedRuntime.connect("127.0.0.1", server.port, "c1")
            ep = rt1.namespace("ns").component("echo").endpoint("generate")
            await ep.serve(echo_engine)
            client = rt2.namespace("ns").component("echo").endpoint(
                "generate").client()
            await client.start()
            await client.wait_for_instances()
            frames = [f async for f in await client.generate({"n": 3, "text": "t"})]
            assert [f["i"] for f in frames] == [0, 1, 2]

            # queue semantics
            await rt1.messaging.queue_push("q1", b"job1")
            assert await rt2.messaging.queue_depth("q1") == 1
            assert await rt2.messaging.queue_pop("q1", timeout=1.0) == b"job1"
            assert await rt2.messaging.queue_pop("q1", timeout=0.05) is None

            # kv watch across connections
            snapshot, events = await rt2.kv.watch_prefix("models/")
            assert snapshot == []
            await rt1.kv.put("models/m1", b"v1")
            ev = await asyncio.wait_for(anext(events), 2.0)
            assert (ev.kind, ev.key, ev.value) == ("put", "models/m1", b"v1")
            await rt1.shutdown()
            await rt2.shutdown()
        finally:
            await server.stop()

    run(main())


def test_dataplane_uses_uds_same_host_and_tcp_when_disabled(monkeypatch):
    """SURVEY §2.1 alternative data plane (the reference's ZMQ/IPC
    option): same-host call-home streams ride the requester's advertised
    unix socket; DYN_DATAPLANE=tcp forces plain TCP."""
    async def roundtrip():
        plane = MemoryPlane()
        server_rt = await DistributedRuntime.create_local(plane, "w")
        client_rt = await DistributedRuntime.create_local(plane, "c")
        ep = server_rt.namespace("ns").component("e").endpoint("g")
        await ep.serve(echo_engine)
        client = client_rt.namespace("ns").component("e").endpoint(
            "g").client()
        await client.start()
        await client.wait_for_instances()
        frames = [f async for f in await client.generate({"n": 3})]
        dp = await client_rt.data_plane()
        stats = (dp.uds_accepts, dp.uds_path)
        await client_rt.shutdown()
        await server_rt.shutdown()
        assert [f["i"] for f in frames] == [0, 1, 2]
        return stats

    # default (auto): the stream arrives via the unix socket
    monkeypatch.delenv("DYN_DATAPLANE", raising=False)
    accepts, path = run(roundtrip())
    assert path is not None and accepts >= 1

    # forced TCP: no UDS listener, streaming still works
    monkeypatch.setenv("DYN_DATAPLANE", "tcp")
    accepts, path = run(roundtrip())
    assert path is None and accepts == 0


def test_served_endpoint_re_role_fence_and_role_routing():
    """ISSUE 12: the real-worker re-registration path. A live served
    instance re-roles decode->prefill through the DRAINING fence; the
    watching client's `ids_for_role` never lists it for the old role
    after the fence event applies, and lists it for the new role only
    after the ready re-put. Role-less instances stay wildcards."""
    async def main():
        plane = MemoryPlane()
        wrt = await DistributedRuntime.create_local(plane, "w-roled")
        art = await DistributedRuntime.create_local(plane, "w-any")
        crt = await DistributedRuntime.create_local(plane, "cl")
        ep = wrt.namespace("ns").component("gen").endpoint("generate")
        served = await ep.serve(echo_engine, metadata={"role": "decode"})
        await art.namespace("ns").component("gen").endpoint(
            "generate").serve(echo_engine)     # role-less wildcard
        client = crt.namespace("ns").component("gen").endpoint(
            "generate").client()
        await client.start()
        await client.wait_for_instances()

        async def wait_for(pred, timeout=5.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while not pred():
                assert asyncio.get_running_loop().time() < deadline, \
                    "condition never held"
                await asyncio.sleep(0.01)

        await wait_for(lambda: "w-roled" in client.ids_for_role("decode"))
        # the role-less instance serves every role
        assert "w-any" in client.ids_for_role("decode")
        assert "w-any" in client.ids_for_role("prefill")
        assert "w-roled" not in client.ids_for_role("prefill")

        res = await served.re_role("prefill", drain_timeout_s=1.0)
        assert res["from_role"] == "decode" and res["to_role"] == "prefill"
        await wait_for(lambda: "w-roled" in client.ids_for_role("prefill"))
        assert "w-roled" not in client.ids_for_role("decode")
        assert "w-roled" not in client.draining_ids()
        # requests still route to the re-roled instance
        frames = [f async for f in await client.direct(
            {"n": 2, "text": "post-re-role"}, "w-roled")]
        assert [f["i"] for f in frames] == [0, 1]

        # mid-fence: a draining re-put removes it from BOTH role lists
        await served.mark_draining()
        await wait_for(
            lambda: "w-roled" not in client.ids_for_role("prefill"))
        assert "w-roled" not in client.ids_for_role("decode")
        assert "w-roled" in client.draining_ids()
        await crt.shutdown()
        await art.shutdown()
        await wrt.shutdown()

    run(main())
