"""Overlapped decode pipeline (engine two-deep host/device loop).

Exactness bar: pipelined streams must be TOKEN-IDENTICAL to the
synchronous loop — greedy and seeded-sampling, including stop-mid-window
and abort-mid-window, both of which force the reconciliation fallback
(the in-flight follow-up window is discarded and the engine re-plans).
Invariant bar (the CPU microbench): the pipelined loop issues exactly one
blocking host sync per committed window, and steady-state windows upload
zero plan arrays. docs/PERF.md has the design and exactness argument.

The two engines (depth=1 reference, depth=2 pipelined) are module-scoped
and reused across tests — engine rebuilds recompile every jitted program
(~4s each on CPU), and serving-realism-wise a reused engine IS the
scenario the pipeline must survive: counter assertions therefore diff
against a snapshot instead of assuming zero.
"""
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams

CFG = ModelConfig(dtype="float32", max_model_len=512)


def make_engine(depth, **kw):
    defaults = dict(
        page_size=64, num_pages=32, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4,
        pipeline_depth=depth)
    defaults.update(kw)
    return NativeEngine(CFG, EngineConfig(**defaults), seed=0)


@pytest.fixture(scope="module")
def eng_sync():
    return make_engine(1)


@pytest.fixture(scope="module")
def eng_pipe():
    return make_engine(2)


def snap(eng):
    return {k: getattr(eng, k) for k in (
        "decode_windows", "pipeline_windows", "pipeline_overlapped",
        "pipeline_fallbacks", "decode_host_syncs", "decode_plan_uploads")}


def delta(eng, before):
    return {k: getattr(eng, k) - v for k, v in before.items()}


def drive(eng, prompts, params_list, tag):
    got = {}
    for i, (pr, p) in enumerate(zip(prompts, params_list)):
        eng.add_request(EngineRequest(f"{tag}{i}", pr, p))
        got[f"{tag}{i}"] = []
    done = set()
    while len(done) < len(prompts):
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
    return [got[f"{tag}{i}"] for i in range(len(prompts))]


def test_pipelined_token_identity_greedy_and_sampled(eng_sync, eng_pipe):
    """depth=2 streams match depth=1 exactly, greedy and seeded-sampled,
    with concurrent requests of different budgets (mid-window finishes
    exercise the reconciliation fallback)."""
    prompts = [list(range(3, 19)), list(range(40, 50))]
    for tag, params in (
        ("g", [SamplingParams(max_tokens=13, temperature=0.0,
                              ignore_eos=True),
               SamplingParams(max_tokens=6, temperature=0.0,
                              ignore_eos=True)]),
        ("s", [SamplingParams(max_tokens=9, temperature=0.9, top_k=12,
                              seed=7, ignore_eos=True),
               SamplingParams(max_tokens=9, temperature=0.7, top_p=0.8,
                              seed=3, ignore_eos=True)]),
    ):
        before = snap(eng_pipe)
        sync = drive(eng_sync, prompts, params, f"id_{tag}_s")
        pipe = drive(eng_pipe, prompts, params, f"id_{tag}_p")
        assert pipe == sync
        # the pipeline actually engaged: windows committed while their
        # follow-up executed on device
        d = delta(eng_pipe, before)
        assert d["pipeline_windows"] > 0
        assert d["pipeline_overlapped"] > 0


def test_stop_mid_window_fallback_token_identity(eng_sync, eng_pipe):
    """A hidden stop id sampled mid-window changes slot membership at
    commit: the in-flight follow-up must be discarded (fallback counter)
    and the stream must still equal the synchronous loop's."""
    prompt = list(range(10, 26))
    ref = eng_sync.generate(
        prompt, SamplingParams(max_tokens=12, ignore_eos=True), "probe")
    stop = ref[5]  # mid-second-window (windows of 4; ref[0] is prefill's)
    p = SamplingParams(max_tokens=12, ignore_eos=True,
                       stop_token_ids=(stop,))
    sync = eng_sync.generate(prompt, p, "stop_s")
    before = snap(eng_pipe)
    pipe = eng_pipe.generate(prompt, p, "stop_p")
    assert pipe == sync == ref[:5]
    assert delta(eng_pipe, before)["pipeline_fallbacks"] >= 1


def test_abort_mid_window_drops_cleanly(eng_sync, eng_pipe):
    """Aborting a request while its window is in flight must drop its
    tokens without corrupting the surviving request's stream (the commit
    identity guard) or the allocator (no double-free)."""
    p = SamplingParams(max_tokens=24, temperature=0.0, ignore_eos=True)
    prompts = [list(range(3, 19)), list(range(40, 50))]
    solo = eng_sync.generate(prompts[0], p, "ab_solo")

    eng = eng_pipe
    for i, pr in enumerate(prompts):
        eng.add_request(EngineRequest(f"ab{i}", pr, p))
    got = {"ab0": [], "ab1": []}
    aborted = False
    finished = set()
    while eng.has_work():
        if eng._pipeline is not None and not aborted \
                and len(got["ab1"]) >= 2:
            # a window is in flight and ab1 has streamed: abort it now
            assert eng.abort("ab1")
            aborted = True
        for ev in eng.step():
            got[ev.request_id].append(ev.token)
            if ev.finished:
                finished.add(ev.request_id)
    assert aborted
    assert "ab0" in finished and "ab1" not in finished
    # survivor is exact; victim never emitted again after the abort
    assert [t for t in got["ab0"] if t is not None] == solo
    free = eng.scheduler.allocator.num_free
    # ab0 finished too, so every page is back exactly once
    assert free == eng.cfg.num_pages


def test_microbench_one_sync_per_window_zero_uploads(eng_pipe,
                                                     monkeypatch):
    """Regression guard on the overlap invariant: with a stable slot set
    whose pages are fully allocated at the first decode plan, the
    pipelined loop issues exactly ONE blocking host sync per committed
    window and uploads plan arrays exactly once."""
    import jax

    eng = eng_pipe
    p = SamplingParams(max_tokens=32, temperature=0.0, ignore_eos=True)
    eng.add_request(EngineRequest("micro", list(range(10, 30)), p))
    while eng.scheduler.waiting:
        eng.step()
    before = snap(eng)

    syncs = {"n": 0}
    real_get = jax.device_get

    def counting_get(x):
        syncs["n"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    while eng.has_work():
        eng.step()
    d = delta(eng, before)
    windows_committed = d["pipeline_windows"]
    assert windows_committed == 32 // eng.cfg.decode_steps
    # <= 1 host sync per window, measured at the jax boundary
    assert syncs["n"] <= windows_committed
    assert d["decode_host_syncs"] == windows_committed
    # prompt(20) + max_tokens(32) fit one 64-token page: allocation never
    # grows mid-request, so only the FIRST window staged host arrays
    assert d["decode_plan_uploads"] == 1
    # and every window after the first committed while its follow-up ran
    assert d["pipeline_overlapped"] >= windows_committed - 2


def test_pipeline_counters_on_metrics(eng_pipe):
    """EngineMetrics carries the pipeline occupancy counters and they
    ADVANCE across a run (the /metrics source of truth; the exporter
    gauge rendering is covered in test_metrics_exporter.py)."""
    eng = eng_pipe
    m0 = eng.metrics()
    p = SamplingParams(max_tokens=16, temperature=0.0, ignore_eos=True)
    eng.generate(list(range(5, 21)), p, "metrics")
    m1 = eng.metrics()
    assert m1.decode_windows > m0.decode_windows
    assert m1.pipeline_windows > m0.pipeline_windows
    assert m1.pipeline_overlapped > m0.pipeline_overlapped
    assert m1.decode_host_syncs > m0.decode_host_syncs
    assert m1.decode_plan_uploads > m0.decode_plan_uploads
    # the wire path keeps them: WorkerMetrics.from_dict round-trip
    import dataclasses

    from dynamo_tpu.kv_router.scoring import WorkerMetrics
    w = WorkerMetrics.from_dict(dataclasses.asdict(m1))
    assert w.pipeline_overlapped == m1.pipeline_overlapped
    assert w.decode_plan_uploads == m1.decode_plan_uploads


def test_ledger_on_is_token_identical_and_samples_every_step(eng_sync,
                                                             eng_pipe):
    """Identity-matrix extension for the step ledger (ISSUE 10): with
    the ledger FORCED ON for the pipelined engine and FORCED OFF for
    the reference, greedy and seeded-sampled streams stay
    token-identical — the ledger only reads host state — while every
    committed window/prefill lands one sample with honest padding and
    occupancy accounting."""
    from dynamo_tpu.observability.ledger import LedgerStats
    prompts = [list(range(3, 19)), list(range(40, 50))]
    stats = LedgerStats()
    old = (eng_pipe.ledger.enabled, eng_pipe.ledger.stats,
           eng_sync.ledger.enabled)
    eng_pipe.ledger.configure(enabled=True)
    eng_pipe.ledger.stats = stats
    eng_sync.ledger.configure(enabled=False)
    try:
        before_len = len(eng_pipe.ledger)
        for tag, params in (
            ("lg", [SamplingParams(max_tokens=11, temperature=0.0,
                                   ignore_eos=True),
                    SamplingParams(max_tokens=5, temperature=0.0,
                                   ignore_eos=True)]),
            ("ls", [SamplingParams(max_tokens=7, temperature=0.9,
                                   top_k=12, seed=7, ignore_eos=True),
                    SamplingParams(max_tokens=7, temperature=0.7,
                                   top_p=0.8, seed=3, ignore_eos=True)]),
        ):
            sync = drive(eng_sync, prompts, params, f"{tag}_s")
            pipe = drive(eng_pipe, prompts, params, f"{tag}_p")
            assert pipe == sync
        recs = eng_pipe.ledger.drain(clear=False)[before_len:]
        assert recs, "ledger recorded nothing with recording enabled"
        kinds = {r["kind"] for r in recs}
        assert "prefill" in kinds and "decode" in kinds
        for r in recs:
            # padding charge is never below the useful tokens, and
            # occupancy reads the real allocator
            assert r["tokens_padded"] >= r["tokens_useful"] > 0
            assert 0 <= r["kv_used"] <= r["kv_total"] == \
                eng_pipe.cfg.num_pages
        # steady-state invariant: re-driving the SAME workload shape
        # dispatches no new (program, bucket) keys — zero recompile
        # events on the ledger (what the llm_engine_recompiles gauge
        # staying flat means in production)
        mark = len(eng_pipe.ledger.drain(clear=False))
        drive(eng_pipe, prompts,
              [SamplingParams(max_tokens=11, temperature=0.0,
                              ignore_eos=True),
               SamplingParams(max_tokens=5, temperature=0.0,
                              ignore_eos=True)], "lg2_p")
        warm = eng_pipe.ledger.drain(clear=False)[mark:]
        assert warm
        assert sum(r["recompiles"] for r in warm) == 0
        m = eng_pipe.metrics()
        assert m.engine_steps == eng_pipe.ledger.steps > 0
        assert m.engine_pad_frac == pytest.approx(
            eng_pipe.ledger.pad_fraction(), abs=1e-4)   # rounded field
    finally:
        eng_pipe.ledger.enabled, eng_pipe.ledger.stats = old[0], old[1]
        eng_sync.ledger.enabled = old[2]


def test_depth_one_is_fully_synchronous(eng_sync):
    """pipeline_depth=1 keeps the old loop: no deferred commits, no
    pipeline counters, events in the same step as the dispatch."""
    eng = eng_sync
    before = snap(eng)
    p = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)
    out = eng.generate(list(range(5, 21)), p, "d1")
    assert len(out) == 8
    assert eng._pipeline is None
    d = delta(eng, before)
    assert d["pipeline_windows"] == 0
    assert d["decode_host_syncs"] == d["decode_windows"] > 0
