"""Cluster-wide shared KV pool tests (ISSUE 13, docs/PERF.md §3e).

The warm-prefix e2e contract: a prefix prefilled on worker A serves on
worker B WITHOUT re-prefilling the matched pages, token-identical to
cold recompute (greedy AND seeded-sampled), and every failure on the
fetch path — entry rot, seeded mid-fetch death, cross-kv_quant-mode
entries, source death — degrades to exactly today's recompute behavior
with zero dropped streams. Plus the routing half: pool-resident
prefixes score as FETCHABLE (priced, never counted as resident), and a
dead worker's pool-source index entries are evicted at watch-event
time so the selector never prices a fetch from a corpse.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.kv_cache import page_hash
from dynamo_tpu.engine.kv_pool import (
    POOL_STATS, AdmissionPrefetcher, PoolQuantMismatch, SharedKvPool,
)
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.runtime.faults import REGISTRY, FaultSchedule, FaultSpec
from dynamo_tpu.runtime.integrity import STATS as INTEGRITY

# same tiny geometry as tests/test_offload.py (jax-cache hits across files)
CFG = ModelConfig(dtype="float32", max_model_len=256)
PAGE = 8
PROMPT = list(range(10, 42))   # 4 pages; the walk matches the 3 full ones
GREEDY = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
SAMPLED = SamplingParams(max_tokens=4, temperature=0.9, top_k=8,
                         seed=1234, ignore_eos=True)


@pytest.fixture(autouse=True)
def clean_state():
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    POOL_STATS.reset()
    yield
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    POOL_STATS.reset()


def arm(site, *specs, seed=0):
    REGISTRY.arm(site, FaultSchedule(seed, list(specs)))


def make_engine(pool=None, wid="", num_pages=32, kv_quant=""):
    eng = NativeEngine(CFG, EngineConfig(
        page_size=PAGE, num_pages=num_pages, max_slots=2,
        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
        max_model_len=256, kv_quant=kv_quant), seed=0)
    if pool is not None:
        eng.attach_kv_pool(pool, wid or "w")
    return eng


def publish_all(eng):
    """Drain sealed pages into the pool and wait for the publish thread
    (the worker step loop does the drain in production)."""
    eng.drain_kv_events()
    eng._pool_stream.drain()


def seeded_pool(prompt=PROMPT, kv_quant=""):
    """A pool holding `prompt`'s pages, published by a throwaway worker A."""
    pool = SharedKvPool(capacity_pages=64)
    a = make_engine(pool, "A", kv_quant=kv_quant)
    a.generate(prompt, GREEDY, "seed-a")
    publish_all(a)
    a.close()
    return pool


# -- warm-prefix e2e ----------------------------------------------------------

def test_cross_worker_reuse_token_identity_greedy_and_sampled():
    """Prefix prefilled on A serves on B through the pool: no
    re-prefill of the matched pages, tokens identical to cold
    recompute under greedy AND seeded sampling."""
    oracle = make_engine()
    expect_g = oracle.generate(PROMPT, GREEDY, "og")
    expect_s = oracle.generate(PROMPT, SAMPLED, "os")

    pool = seeded_pool()
    b = make_engine(pool, "B")
    assert b.generate(PROMPT, GREEDY, "bg") == expect_g
    # the 3 full prefix pages were FETCHED, not recomputed: the walk
    # claimed them from the pool and charged them as cached
    assert b.scheduler.pool_fetched_pages == 3
    assert POOL_STATS.fetch_hits == 3
    assert b.scheduler._prefix_hits >= 3

    b2 = make_engine(pool, "B2")
    assert b2.generate(PROMPT, SAMPLED, "bs") == expect_s
    assert b2.scheduler.pool_fetched_pages == 3
    b.close(); b2.close(); oracle.close()


def test_pool_entry_rot_quarantined_and_recomputed_not_served():
    """At-rest rot in a pool entry: the fetch-time checksum verify
    quarantines it (entry removed, never served) and the page is
    recomputed — tokens stay identical to cold."""
    expect = make_engine().generate(PROMPT, GREEDY, "o")
    pool = seeded_pool()
    h0 = page_hash(0, PROMPT[:PAGE])
    with pool._mu:   # rot the first page's stored bytes
        e = pool._entries[h0]
        rotten = np.array(e.arrays[0])
        rotten[0, 0, 0, 0] += 1.0
        e.arrays = (rotten,) + e.arrays[1:]
    b = make_engine(pool, "B")
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert POOL_STATS.quarantined == 1
    assert INTEGRITY.quarantined >= 1
    assert h0 not in pool          # quarantine removed the rotten entry
    # the walk broke at page 0: nothing fetched, everything recomputed
    assert b.scheduler.pool_fetched_pages == 0
    b.close()


def test_seeded_mid_fetch_death_salvages_to_recompute():
    """The seeded mid-fetch-death case (acceptance): page 2 of the
    fetch chain dies (corruption at the pool.fetch failpoint), the
    walk keeps the committed page and recomputes the tail — zero
    dropped streams, greedy AND seeded-sampled identity."""
    oracle = make_engine()
    expect_g = oracle.generate(PROMPT, GREEDY, "og")
    expect_s = oracle.generate(PROMPT, SAMPLED, "os")

    pool = seeded_pool()
    arm("pool.fetch", FaultSpec("corrupt", p=1.0, n=1, skip=1))
    b = make_engine(pool, "B")
    assert b.generate(PROMPT, GREEDY, "bg") == expect_g
    assert b.scheduler.pool_fetched_pages == 1   # committed prefix kept
    assert POOL_STATS.quarantined == 1           # page 2 died mid-fetch
    REGISTRY.disarm()

    # same seeded death under sampling, fresh engine + fresh pool
    POOL_STATS.reset()
    pool2 = seeded_pool()
    arm("pool.fetch", FaultSpec("corrupt", p=1.0, n=1, skip=1))
    b2 = make_engine(pool2, "B2")
    assert b2.generate(PROMPT, SAMPLED, "bs") == expect_s
    assert POOL_STATS.quarantined == 1
    b.close(); b2.close(); oracle.close()


def test_cross_kv_quant_mode_fetch_rejected_by_name():
    """An int8-published page fetched by an unquantized engine is
    rejected BY NAME (PoolQuantMismatch naming both modes), walks as a
    miss, and the request recomputes correctly — never a silent cast."""
    pool = seeded_pool(kv_quant="int8")
    h0 = page_hash(0, PROMPT[:PAGE])
    with pytest.raises(PoolQuantMismatch) as ei:
        pool.fetch(h0, "")
    assert "int8" in str(ei.value) and "off" in str(ei.value)
    assert POOL_STATS.quant_rejected == 1

    expect = make_engine().generate(PROMPT, GREEDY, "o")
    b = make_engine(pool, "B")   # unquantized engine, int8 pool entries
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert b.scheduler.pool_fetched_pages == 0
    assert POOL_STATS.quant_rejected >= 2
    b.close()


def test_dedup_identical_int8_pages_from_two_workers_keeps_one_entry():
    """Two int8 workers prefill the identical prompt: the pool keeps
    ONE byte copy per page, records both sources, and counts the
    second publish as dedup."""
    pool = SharedKvPool(capacity_pages=64)
    for wid in ("A1", "A2"):
        eng = make_engine(pool, wid, kv_quant="int8")
        eng.generate(PROMPT, GREEDY, f"seed-{wid}")
        publish_all(eng)
        eng.close()
    h0 = page_hash(0, PROMPT[:PAGE])
    with pool._mu:
        entry = pool._entries[h0]
        assert entry.sources == {"A1", "A2"}
        assert entry.mode == "int8"
        assert len(entry.arrays) == 4    # int8 values + f32 scale rows
        n_entries = len(pool._entries)
    assert POOL_STATS.publishes == n_entries      # one per unique hash
    assert POOL_STATS.dedup_hits >= 3             # A2's prefix pages dedup'd
    # bytes counted once per kept copy
    assert POOL_STATS.bytes == sum(
        e.nbytes for e in pool._entries.values())
    # an int8 consumer serves from the dedup'd entries
    expect = make_engine(kv_quant="int8").generate(PROMPT, GREEDY, "o")
    b = make_engine(pool, "B", kv_quant="int8")
    assert b.generate(PROMPT, GREEDY, "b") == expect
    assert b.scheduler.pool_fetched_pages == 3
    b.close()


# -- prefetch (PRESERVE window) ----------------------------------------------

def test_prefetch_racing_cancel_leaves_no_leaked_hbm_pages():
    """Prefetched pages are sealed into the REUSABLE pool: a request
    that never arrives (admission cancel / deadline expiry) leaks
    nothing — every page stays evictable and num_free is unchanged."""
    pool = seeded_pool()
    b = make_engine(pool, "B", num_pages=16)
    # the prefetch walk covers all 4 full pages (the admission walk
    # leaves >=1 token to recompute, so it will use the leading 3)
    warmed = b.prefetch_pool_pages(PROMPT)
    assert warmed == 4
    # reusable pages count as free: nothing is held for the request
    assert b.scheduler.allocator.num_free == 16
    # double prefetch is a no-op (HBM lookup short-circuits)
    assert b.prefetch_pool_pages(PROMPT) == 0
    # the "cancelled" request never arrives; a DIFFERENT workload can
    # take every page (prefetched ones evict like any reusable entry)
    other = [(500 + i) % 250 + 1 for i in range(80)]   # 10 pages
    expect = make_engine().generate(other, GREEDY, "o")
    assert b.generate(other, GREEDY, "b-other") == expect
    assert b.scheduler.allocator.num_free == 16   # all freed after finish
    b.close()


def test_prefetch_serves_from_hbm_and_counts_window_outcome():
    pool = seeded_pool()
    expect = make_engine().generate(PROMPT, GREEDY, "o")
    b = make_engine(pool, "B")
    assert b.prefetch_pool_pages(PROMPT) == 4
    assert POOL_STATS.prefetch_pages == 4
    assert b.generate(PROMPT, GREEDY, "b") == expect
    # served from HBM: the admission walk fetched nothing from the pool
    assert b.scheduler.pool_fetched_pages == 0
    b.close()


def test_admission_prefetcher_warms_target_worker():
    """The frontend-facing wrapper: tokens -> target worker ->
    engine.prefetch_pool_pages between device steps, with the
    hit-vs-late window accounting."""
    from dynamo_tpu.llm.worker import NativeEngineWorker

    async def main():
        pool = seeded_pool()
        worker = NativeEngineWorker(make_engine(pool, "B"))
        await worker.start()
        try:
            pref = AdmissionPrefetcher(
                pool, tokens_fn=lambda req: req,
                target_fn=lambda toks: worker, page_size=PAGE)
            assert pref.matched_pages(PROMPT) == 4
            admitted = asyncio.Event()
            assert await pref.prefetch(PROMPT, admitted) == 4
            assert POOL_STATS.prefetch_hits == 1
            assert POOL_STATS.prefetch_late == 0
            # window already over -> a fresh warm counts late; an
            # already-warm prompt (0 pages) counts neither
            admitted.set()
            assert await pref.prefetch(PROMPT, admitted) == 0
            assert POOL_STATS.prefetch_late == 0
            # unknown prompt: no pool match, no engine round trip
            assert await pref.prefetch([9] * 32, admitted) == 0
        finally:
            await worker.stop()

    asyncio.run(asyncio.wait_for(main(), 60))


# -- pool store semantics -----------------------------------------------------

def test_source_eviction_drops_only_single_source_entries():
    pool = SharedKvPool(capacity_pages=8)
    page = (np.ones((1, 1, 2, 2), np.float32),
            np.ones((1, 1, 2, 2), np.float32))
    assert pool.publish("A", 1, 0, 11, page) == "new"
    assert pool.publish("B", 1, 0, 11, page) == "dup"
    assert pool.publish("A", 2, 1, 22, page) == "new"
    assert pool.evict_source("A") == 1      # entry 2 was A-only
    assert 1 in pool and 2 not in pool
    with pool._mu:
        assert pool._entries[1].sources == {"B"}
    assert POOL_STATS.source_evictions == 1


def test_capacity_eviction_emits_removed_events_per_source():
    pool = SharedKvPool(capacity_pages=2)
    page = (np.zeros((1, 1, 2, 2), np.float32),) * 2
    pool.publish("A", 1, 0, 11, page)
    pool.publish("A", 2, 1, 22, page)
    pool.drain_events("A")
    pool.publish("A", 3, 2, 33, page)     # LRU-evicts hash 1
    assert 1 not in pool and POOL_STATS.evicted == 1
    events = pool.drain_events("A")
    assert ("removed", 0, 1, 0, 11) in events
    assert ("stored", 0, 3, 2, 33) in events


# -- routing: fetchable prefixes ---------------------------------------------

class FakeClient:
    def __init__(self, instances):
        self.instances = instances


def _endpoints(**workers):
    from dynamo_tpu.kv_router.scoring import (
        ProcessedEndpoints, WorkerMetrics,
    )
    return ProcessedEndpoints({
        wid: WorkerMetrics(**kw) for wid, kw in workers.items()})


def _sched(model, block_size=16, **kw):
    import random

    from dynamo_tpu.kv_router.scheduler import (
        KvScheduler, TransferAwareSelector,
    )
    kw.setdefault("rng", random.Random(0))
    kw.setdefault("default_block_bytes", 1 << 20)
    return KvScheduler(block_size=block_size,
                       selector=TransferAwareSelector(cost_model=model,
                                                      **kw))


def _model(**bw):
    from dynamo_tpu.observability.fleet import TransferCostModel
    m = TransferCostModel()
    for link, bytes_per_s in bw.items():
        m.observe(link, int(bytes_per_s), 1.0)
    return m


def test_selector_pool_blocks_reduce_bytes_to_move_and_join_overlap():
    from dynamo_tpu.kv_router.indexer import MatchResult
    model = _model(w1=1 << 28, w2=1 << 28)
    sched = _sched(model)
    sched.update_endpoints(_endpoints(
        w1=dict(request_total_slots=8, kv_total_blocks=100),
        w2=dict(request_total_slots=8, kv_total_blocks=100)))
    # 10 required blocks, nothing resident, 6 fetchable from the pool
    sched.schedule(160, MatchResult(), pool_matched=6)
    comps = sched.selector.last_components
    for w in ("w1", "w2"):
        assert comps[w]["pool_blocks"] == 6
        assert comps[w]["transfer_bytes"] == 4 * (1 << 20)   # misses only
        assert comps[w]["pool_fetch_bytes"] == 6 * (1 << 20)
        assert comps[w]["overlap"] == pytest.approx(6 * 16 / 160)
    from dynamo_tpu.kv_router.stats import ROUTER_STATS
    assert ROUTER_STATS.pool_scored >= 1
    assert ROUTER_STATS.last_pool_fetch_blocks == 6


def test_selector_resident_beats_fetchable_at_equal_depth():
    """Equal reuse depth, but the fetch costs wire time: the worker
    that already HOLDS the prefix must win."""
    from dynamo_tpu.kv_router.indexer import MatchResult
    model = _model(holder=1 << 26, fetcher=1 << 26)   # equal 64 MiB/s links
    sched = _sched(model)
    sched.update_endpoints(_endpoints(
        holder=dict(request_total_slots=8, kv_total_blocks=100),
        fetcher=dict(request_total_slots=8, kv_total_blocks=100)))
    picked = sched.schedule(160, MatchResult(scores={"holder": 6}),
                            pool_matched=6)
    assert picked == "holder"
    comps = sched.selector.last_components
    assert comps["holder"]["pool_blocks"] == 0
    assert comps["fetcher"]["pool_blocks"] == 6
    assert comps["fetcher"]["transfer_s"] > comps["holder"]["transfer_s"]


def test_selector_pool_match_beats_no_reuse_on_fast_links():
    """A fetchable prefix on a fast link beats recomputing from
    scratch — the LMCache shape of the decision."""
    from dynamo_tpu.kv_router.indexer import MatchResult
    model = _model(w1=1 << 30, w2=1 << 30)
    sched = _sched(model)
    sched.update_endpoints(_endpoints(
        w1=dict(request_total_slots=8, kv_total_blocks=100),
        w2=dict(request_total_slots=8, kv_total_blocks=100,
                request_active_slots=1)))
    # without the pool, w2's load loses; the fetchable prefix is shared
    # so ranking is unchanged — pool depth is worker-independent
    assert sched.schedule(160, MatchResult(), pool_matched=8) == "w1"
    comps = sched.selector.last_components
    assert comps["w1"]["overlap"] > 0


def test_router_split_pool_scores_fences_corpse_sources():
    """pool:{w} scores leave the resident score map, fold into ONE
    fetchable depth, and a source absent from the live instance set is
    never priced (the watch fence)."""
    from dynamo_tpu.kv_router.indexer import MatchResult
    from dynamo_tpu.kv_router.router import KvRouter
    router = KvRouter(object(), FakeClient({"w1": {}, "w2": {}}),
                      block_size=4)
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3, "pool:dead": 5})
    assert router._split_pool_scores(overlap) == 3   # corpse depth ignored
    assert overlap.scores == {"w1": 1}


def test_router_split_pool_scores_fences_dead_pool_hosts():
    """PR 17 satellite: liveness one layer DOWN from the source worker —
    the pool HOSTS (ring membership). While ≥1 member is live a replica
    walk can still serve every entry, so pool depth keeps pricing; the
    moment the watch deletes the last `pool-host:` instance the
    fetchable prefix is worth zero, at event time, before any fetch
    would hang on a corpse host."""
    from dynamo_tpu.kv_router.indexer import MatchResult
    from dynamo_tpu.kv_router.router import KvRouter
    from dynamo_tpu.runtime.placement import (
        PoolMembership, pool_host_instance_id,
    )
    m = PoolMembership()
    router = KvRouter(object(), FakeClient({"w1": {}}), block_size=4,
                      pool_membership=m)
    # the router's watch listener forwards `pool-host:` instance events
    # here (see KvRouter on_instance); drive the same membership shape
    m.on_instance("put", pool_host_instance_id("ph0"), {})
    m.on_instance("put", pool_host_instance_id("ph1"), {})
    assert set(m.live_hosts()) == {"ph0", "ph1"}   # watch feeds the ring
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3})
    assert router._split_pool_scores(overlap) == 3
    # one host down: replication still serves — still priced
    m.on_instance("delete", pool_host_instance_id("ph0"), {})
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3})
    assert router._split_pool_scores(overlap) == 3
    # LAST host down: zero at event time
    m.on_instance("delete", pool_host_instance_id("ph1"), {})
    overlap = MatchResult(scores={"w1": 1, "pool:w1": 3})
    assert router._split_pool_scores(overlap) == 0
    assert overlap.scores == {"w1": 1}


def test_watch_delete_evicts_pool_source_entries_at_event_time():
    """Satellite fix: a dead worker's POOL-source index entries go at
    watch-delete time, mirroring the PR 4 worker-entry eviction — the
    selector must never price a fetch from a corpse."""
    from dynamo_tpu.kv_router.publisher import (
        KvEventPublisher, KvMetricsPublisher,
    )
    from dynamo_tpu.kv_router.router import KvRouter
    from dynamo_tpu.kv_router.scoring import WorkerMetrics
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    async def main():
        plane = MemoryPlane()
        worker_rts, pubs = [], {}
        for wid in ("w1", "w2"):
            rt = await DistributedRuntime.create_local(plane, wid)
            comp = rt.namespace("ns").component("worker")
            mpub = KvMetricsPublisher()
            mpub.update(WorkerMetrics(
                request_active_slots=0, request_total_slots=8,
                kv_active_blocks=0, kv_total_blocks=100))

            async def engine(request, context, wid=wid):
                yield {"worker": wid}

            await comp.endpoint("generate").serve(
                engine, stats_handler=mpub.stats_handler)
            pubs[wid] = comp
            worker_rts.append(rt)

        rrt = await DistributedRuntime.create_local(plane, "router")
        comp = rrt.namespace("ns").component("worker")
        client = comp.endpoint("generate").client()
        await client.start()
        await client.wait_for_instances()
        router = await KvRouter(comp, client, block_size=4,
                                scrape_interval_s=60.0).start()
        await router.aggregator.scrape_once()

        # w2 publishes two prefix pages into the POOL namespace
        toks = list(range(100, 116))
        pool = SharedKvPool(capacity_pages=8)
        page = (np.zeros((1, 1, 2, 2), np.float32),) * 2
        from dynamo_tpu.engine.kv_cache import tokens_hash
        parent = 0
        for i in range(2):
            ptoks = toks[i * 4:(i + 1) * 4]
            h = page_hash(parent, ptoks)
            pool.publish("w2", h, parent, tokens_hash(ptoks), page)
            parent = h
        await KvEventPublisher(pubs["w2"], "pool:w2") \
            .publish_allocator_events(pool.drain_events("w2"))
        await asyncio.sleep(0.1)   # event pump

        scores = router.find_matches_for_tokens(toks).scores
        assert scores == {"pool:w2": 2}
        # schedule() prices the fetchable depth (live source) without
        # ranking anyone as resident
        await router.schedule(toks)
        assert router.scheduler.selector.last_pick["pool_blocks"] == 2

        # w2 dies: the watch delete purges pool:w2 at EVENT time — no
        # scrape happens (interval 60s) before the assertion
        await worker_rts[1].shutdown()
        await asyncio.sleep(0.2)
        assert router.find_matches_for_tokens(toks).scores == {}
        await router.schedule(toks)
        assert router.scheduler.selector.last_pick["pool_blocks"] == 0

        await router.stop()
        await rrt.shutdown()
        await worker_rts[0].shutdown()

    asyncio.run(asyncio.wait_for(main(), 60))
