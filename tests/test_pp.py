"""Pipeline parallelism tests: pp_forward oracle parity on the CPU mesh.

VERDICT r2 next #8: a real microbatched pipeline over the "pp" mesh axis
(the reference delegates PP to vLLM, vllm_inc.py:38). The oracle is the
single-mesh models/llama.forward; pp must be bit-compatible in f32.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.models import llama
from dynamo_tpu.models.llama import AttnMetadata
from dynamo_tpu.models.pp import pp_cache_sharding, pp_forward, pp_param_shardings
from dynamo_tpu.parallel.mesh import make_mesh

CFG = ModelConfig(dtype="float32", num_layers=4, max_model_len=128)
PAGE = 8
# enough pages that every test row gets a DISJOINT page range (aliased
# pages would make results order-dependent and the oracle meaningless)
NPAGES = 64


def make_inputs(b, tq, kv_len):
    """A prefill-shaped step: rows write positions [kv_len-tq, kv_len)."""
    rng = np.random.RandomState(0)
    tokens = rng.randint(1, CFG.vocab_size, (b, tq)).astype(np.int32)
    positions = np.tile(np.arange(kv_len - tq, kv_len, dtype=np.int32),
                        (b, 1))
    pages_per_seq = -(-CFG.max_model_len // PAGE)
    page_table = np.stack([
        np.arange(i * pages_per_seq, (i + 1) * pages_per_seq,
                  dtype=np.int32) % NPAGES
        for i in range(b)])
    kv_lens = np.full((b,), kv_len, np.int32)
    write_idx = np.stack([
        page_table[i, positions[i] // PAGE] * PAGE + positions[i] % PAGE
        for i in range(b)]).astype(np.int32)
    return (jnp.asarray(tokens),
            AttnMetadata(positions=jnp.asarray(positions),
                         page_table=jnp.asarray(page_table),
                         kv_lens=jnp.asarray(kv_lens),
                         write_idx=jnp.asarray(write_idx)))


@pytest.mark.parametrize("pp,tp,n_micro", [(2, 1, 2), (4, 1, 4), (2, 2, 2),
                                           (2, 1, 1)])
def test_pp_forward_matches_single_mesh(pp, tp, n_micro):
    params = llama.init_params(jax.random.PRNGKey(0), CFG)
    cache = llama.init_cache(CFG, num_pages=NPAGES, page_size=PAGE)
    b, tq, kv_len = 4, PAGE, PAGE
    tokens, meta = make_inputs(b, tq, kv_len)

    expect_logits, expect_cache = jax.jit(
        lambda p, c: llama.forward(p, CFG, tokens, c, meta))(params, cache)

    mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
    from jax.sharding import NamedSharding
    shd = jax.tree.map(lambda s: NamedSharding(mesh, s),
                       pp_param_shardings(CFG),
                       is_leaf=lambda x: isinstance(
                           x, jax.sharding.PartitionSpec))
    params_pp = jax.device_put(params, shd)
    cache_shd = NamedSharding(mesh, pp_cache_sharding())
    cache_pp = jax.device_put(
        llama.init_cache(CFG, num_pages=NPAGES, page_size=PAGE),
        {"k": cache_shd, "v": cache_shd})

    got_logits, got_cache = jax.jit(
        lambda p, c: pp_forward(p, CFG, tokens, c, meta, mesh,
                                n_micro=n_micro))(params_pp, cache_pp)

    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(expect_logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_cache["k"]),
                               np.asarray(expect_cache["k"]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_cache["v"]),
                               np.asarray(expect_cache["v"]),
                               rtol=1e-5, atol=1e-5)


GEMMA2_CFG = ModelConfig(
    dtype="float32", num_layers=4, max_model_len=128, embed_scale=8.0,
    norm_plus_one=True, mlp_act="gelu_tanh", post_norms=True,
    attn_softcap=50.0, final_softcap=30.0, query_scale=32 ** -0.5,
    sliding_window=6, tie_word_embeddings=True)


@pytest.mark.parametrize("pp,tp", [(2, 1), (2, 2)])
def test_pp_forward_gemma2_matches_single_mesh(pp, tp):
    """Gemma-2-class configs (post-norms, soft-caps, query scaling, and
    ALTERNATING sliding windows threaded through the stage scan as a
    pp-sharded per-layer operand) stay oracle-exact on pp meshes."""
    cfg = GEMMA2_CFG
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    cache = llama.init_cache(cfg, num_pages=NPAGES, page_size=PAGE)
    b, tq, kv_len = 4, PAGE, PAGE
    tokens, meta = make_inputs(b, tq, kv_len)

    expect_logits, expect_cache = jax.jit(
        lambda p, c: llama.forward(p, cfg, tokens, c, meta))(params, cache)

    mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
    from jax.sharding import NamedSharding
    shd = jax.tree.map(lambda s: NamedSharding(mesh, s),
                       pp_param_shardings(cfg),
                       is_leaf=lambda x: isinstance(
                           x, jax.sharding.PartitionSpec))
    params_pp = jax.device_put(params, shd)
    cache_shd = NamedSharding(mesh, pp_cache_sharding())
    cache_pp = jax.device_put(
        llama.init_cache(cfg, num_pages=NPAGES, page_size=PAGE),
        {"k": cache_shd, "v": cache_shd})
    got_logits, got_cache = jax.jit(
        lambda p, c: pp_forward(p, cfg, tokens, c, meta, mesh))(
            params_pp, cache_pp)
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(expect_logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_cache["k"]),
                               np.asarray(expect_cache["k"]),
                               rtol=1e-5, atol=1e-5)


def test_pp_engine_gemma2_generates_identically():
    """Full engine on pp=2: Gemma-2-class greedy decode (multi-token pp
    windows incl. the sliding-window boundary) matches the single-device
    engine token-for-token."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    ecfg = EngineConfig(page_size=8, num_pages=64, max_slots=2,
                        max_prefill_chunk=16, prefill_buckets=(8, 16),
                        max_model_len=128)
    params = SamplingParams(max_tokens=10, temperature=0.0, ignore_eos=True)
    prompts = [list(range(3, 15)), list(range(40, 60))]

    oracle = NativeEngine(GEMMA2_CFG, ecfg, seed=0)
    expect = [oracle.generate(p, params, f"o{i}")
              for i, p in enumerate(prompts)]
    mesh = make_mesh(pp=2, tp=1, devices=jax.devices()[:2])
    eng = NativeEngine(GEMMA2_CFG, ecfg, mesh=mesh, seed=0)
    got, max_one = _drive_engine(eng, prompts, params)
    assert got == expect
    assert max_one > 1  # windowed pp decode, not per-token


def _drive_engine(eng, prompts, params):
    """Submit all prompts, run to completion; returns (tokens per request,
    max tokens any one request received from a single host dispatch)."""
    from dynamo_tpu.engine.scheduler import EngineRequest

    got = {}
    for i, p in enumerate(prompts):
        eng.add_request(EngineRequest(f"r{i}", p, params))
        got[f"r{i}"] = []
    max_tokens_one_dispatch = 0
    while eng.has_work():
        per_req = {}
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
                per_req[ev.request_id] = per_req.get(ev.request_id, 0) + 1
        if per_req:
            max_tokens_one_dispatch = max(max_tokens_one_dispatch,
                                          max(per_req.values()))
    return [got[f"r{i}"] for i in range(len(prompts))], \
        max_tokens_one_dispatch


def test_pp_engine_generates_identically():
    """Full engine on a pp=2 mesh (pp=2 x tp=2 too): greedy tokens match the
    single-device engine exactly — the 'dryrun mesh pp=2 generating
    correctly' bar from VERDICT r2 next #8."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    ecfg = EngineConfig(page_size=8, num_pages=64, max_slots=2,
                        max_prefill_chunk=16, prefill_buckets=(8, 16),
                        max_model_len=128)
    params = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(3, 15)), list(range(40, 60))]

    oracle = NativeEngine(CFG, ecfg, seed=0)
    expect = [oracle.generate(p, params, f"o{i}")
              for i, p in enumerate(prompts)]

    for pp, tp in ((2, 1), (2, 2)):
        mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
        eng = NativeEngine(CFG, ecfg, mesh=mesh, seed=0)
        # multi-token pp decode (VERDICT r3 weak #7): the window survives
        # pp meshes instead of being forced to 1
        assert eng.pp == pp and eng.cfg.decode_steps == ecfg.decode_steps
        got, max_tokens_one_dispatch = _drive_engine(eng, prompts, params)
        assert got == expect, f"pp={pp} tp={tp} diverged"
        # the microbatch round-robin serves >1 token per host dispatch
        assert max_tokens_one_dispatch > 1, \
            f"pp={pp} tp={tp}: decode still per-token"


def test_pp_engine_sampled_window_matches_oracle():
    """VERDICT r4 #6: sampled plans (temperature / top-k / top-p) get
    windowed pp decode too — >1 token per host dispatch, token-exact vs
    the single-mesh engine at a fixed seed (the pp window samples through
    the same sample_logits tail with the same (seed, counter) keys).
    pp=2 x tp=2 covers sampling over the all_gathered vocab-sharded
    logits too."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    ecfg = EngineConfig(page_size=8, num_pages=64, max_slots=2,
                        max_prefill_chunk=16, prefill_buckets=(8, 16),
                        max_model_len=128)
    params = SamplingParams(max_tokens=8, temperature=0.8, top_k=40,
                            top_p=0.95, seed=1234, ignore_eos=True)
    prompts = [list(range(3, 15)), list(range(40, 60))]

    oracle = NativeEngine(CFG, ecfg, seed=0)
    expect = [oracle.generate(p, params, f"o{i}")
              for i, p in enumerate(prompts)]

    for pp, tp in ((2, 1), (2, 2)):
        mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
        eng = NativeEngine(CFG, ecfg, mesh=mesh, seed=0)
        got, max_tokens_one_dispatch = _drive_engine(eng, prompts, params)
        assert got == expect, f"sampled pp={pp} tp={tp} diverged"
        # the sampled plan went through the window, not per-token dispatch
        assert max_tokens_one_dispatch > 1, \
            f"sampled pp={pp} tp={tp} decode still per-token"


def test_pp_tied_embeddings_engine_matches():
    """tie_word_embeddings + pp: the vocab-sharded embedding (P("tp",
    None) rows, _embed_lookup masked gather + psum) doubles as the
    vocab-sharded head; tokens match the single-device engine."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    cfg = ModelConfig(dtype="float32", max_model_len=128,
                      tie_word_embeddings=True)
    ecfg = EngineConfig(page_size=8, num_pages=64, max_slots=2,
                        max_prefill_chunk=16, prefill_buckets=(8, 16),
                        max_model_len=128)
    p = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    prompt = list(range(9, 25))
    oracle = NativeEngine(cfg, ecfg, seed=0).generate(prompt, p, "o")
    mesh = make_mesh(pp=2, tp=2, devices=jax.devices()[:4])
    got = NativeEngine(cfg, ecfg, mesh=mesh, seed=0).generate(
        prompt, p, "t")
    assert got == oracle


def test_pp_decode_step_matches():
    """tq=1 decode-shaped step through the pipeline (the engine's pp decode
    path) against the single-mesh oracle, including the KV row it writes."""
    params = llama.init_params(jax.random.PRNGKey(1), CFG)
    b, kv_len = 4, 24

    # build a warm cache by prefilling kv_len-1 tokens, then decode 1 token
    tokens_p, meta_p = make_inputs(b, PAGE, PAGE)
    cache = llama.init_cache(CFG, num_pages=NPAGES, page_size=PAGE)
    _, cache = jax.jit(
        lambda p, c: llama.forward(p, CFG, tokens_p, c, meta_p))(
            params, cache)

    tokens_d, meta_d = make_inputs(b, 1, PAGE + 1)
    expect_logits, expect_cache = jax.jit(
        lambda p, c: llama.forward(p, CFG, tokens_d, c, meta_d))(
            params, cache)

    mesh = make_mesh(pp=2, devices=jax.devices()[:2])
    from jax.sharding import NamedSharding
    shd = jax.tree.map(lambda s: NamedSharding(mesh, s),
                       pp_param_shardings(CFG),
                       is_leaf=lambda x: isinstance(
                           x, jax.sharding.PartitionSpec))
    params_pp = jax.device_put(params, shd)
    cache_shd = NamedSharding(mesh, pp_cache_sharding())
    cache_pp = jax.device_put(jax.device_get(cache),
                              {"k": cache_shd, "v": cache_shd})

    got_logits, got_cache = jax.jit(
        lambda p, c: pp_forward(p, CFG, tokens_d, c, meta_d, mesh))(
            params_pp, cache_pp)
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(expect_logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_cache["k"]),
                               np.asarray(expect_cache["k"]),
                               rtol=1e-5, atol=1e-5)
