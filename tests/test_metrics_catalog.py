"""Metric-catalog completeness (ISSUE 10 satellite): every family
documented in docs/OBSERVABILITY.md §9 must actually RENDER (HELP/TYPE
lines) on its surface after a mini aggregated serve + one disagg
request. This is the runtime half of the two-sided gate whose static
half is dynalint R15 (registration -> catalog): R15 stops undocumented
families; this test stops documented-but-unplumbed ones — the silent
gauge-plumbing regression class where a family is registered in one
process but dropped from a render fold, or documented and never
registered at all.
"""
import asyncio
import os
import re

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md")

_FAM_RE = re.compile(r"`(llm_[a-z0-9_]+)`")


def parse_catalog():
    """{family: surface} from the §9 table (same section dynalint R15
    reads); surfaces: frontend / exporter / both / watchdog."""
    text = open(DOC).read()
    m = re.search(r"^##[^\n]*metric catalog.*?$", text, re.I | re.M)
    assert m, "docs/OBSERVABILITY.md lost its metric catalog section"
    tail = text[m.end():]
    nxt = re.search(r"^## ", tail, re.M)
    section = tail[:nxt.start()] if nxt else tail
    out = {}
    for line in section.splitlines():
        if not line.startswith("|") or line.startswith("|---"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 3 or cells[1] not in ("frontend", "exporter",
                                              "both", "watchdog"):
            continue
        for fam in _FAM_RE.findall(cells[2]):
            out[fam] = cells[1]
    return out


def test_catalog_parses_and_is_substantial():
    catalog = parse_catalog()
    assert len(catalog) > 100     # the full telemetry surface
    assert catalog["llm_workers"] == "exporter"
    assert catalog["llm_ttft_seconds"] == "both"
    assert catalog["llm_engine_steps_total"] == "frontend"
    assert catalog["llm_slo_firing"] == "watchdog"


@pytest.fixture(scope="module")
def rendered_surfaces():
    """One mini aggregated serve + one disagg request, then every
    surface's /metrics body."""
    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, LocalTransferBackend,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.frontend.service import HttpService
    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.observability.exporter import MetricsExporter
    from dynamo_tpu.observability.slo import SloSpec, SloWatchdog
    from dynamo_tpu.observability.timeseries import SeriesStore
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, StopConditions,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane
    from tests.http_client import request

    # the same tiny geometry as test_disagg (jax compile cache hit)
    CFG = ModelConfig(dtype="float32", max_model_len=512)

    def make_engine():
        return NativeEngine(CFG, EngineConfig(
            page_size=8, num_pages=64, max_slots=4, max_prefill_chunk=32,
            prefill_buckets=(8, 16, 32), max_model_len=512), seed=0)

    from dynamo_tpu.protocols.openai import (
        ChatCompletionChunk, ChatStreamChoice, new_response_id, now,
    )

    class TokenEngine:
        """Minimal streaming chat fake (test_frontend's CounterEngine
        shape): one content chunk + a stop chunk."""

        async def generate_chat(self, req, context):
            gen_id, created = new_response_id("chatcmpl"), now()
            yield ChatCompletionChunk(
                id=gen_id, created=created, model=req.model,
                choices=[ChatStreamChoice(
                    index=0, delta={"role": "assistant", "content": "ok"})])
            yield ChatCompletionChunk(
                id=gen_id, created=created, model=req.model,
                choices=[ChatStreamChoice(index=0, delta={},
                                          finish_reason="stop")])

    async def main():
        # -- aggregated serve: one HTTP chat completion ------------------
        svc = await HttpService("127.0.0.1", 0).start()
        svc.models.chat["m"] = TokenEngine()
        status, _ = await request(
            "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
            {"model": "m", "messages": [{"role": "user",
                                         "content": "hi"}]})
        assert status == 200

        # -- one disagg request (remote prefill + local KV transfer) ----
        plane = MemoryPlane()
        transfer = LocalTransferBackend()
        queue = PrefillQueue(plane.messaging, "ns", "tiny")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=4,
                                     model="tiny")
        decode = DisaggDecodeWorker(make_engine(), plane.messaging,
                                    router, queue, worker_id="dec-0",
                                    prefill_timeout_s=30.0)
        transfer.register("dec-0", decode)
        prefill = PrefillWorker(NativeEngineWorker(make_engine()), queue,
                                transfer, plane.messaging)
        await decode.start()
        await prefill.start()
        try:
            req = PreprocessedRequest(
                request_id="cat1", token_ids=list(range(100, 120)),
                stop=StopConditions(max_tokens=4, ignore_eos=True))
            async for _ in decode.generate(
                    req.model_dump(exclude_none=True), Context("cat1")):
                pass
        finally:
            await prefill.stop()
            await decode.stop()
        _, frontend_raw = await request(
            "127.0.0.1", svc.port, "GET", "/metrics")
        frontend_body = frontend_raw.decode()
        await svc.stop()

        # -- exporter over one live worker -------------------------------
        wrt = await DistributedRuntime.create_local(plane, "w0")
        ep = wrt.namespace("ns").component("worker").endpoint("generate")

        async def fake(request_, context):
            yield {}

        await ep.serve(fake, stats_handler=lambda: {
            "request_active_slots": 1, "request_total_slots": 4,
            "kv_active_blocks": 2, "kv_total_blocks": 16,
            "num_requests_waiting": 0, "gpu_cache_usage_perc": 0.1,
            "gpu_prefix_cache_hit_rate": 0.5})
        ert = await DistributedRuntime.create_local(plane, "exp")
        exporter = MetricsExporter(ert, "ns", "worker", port=0,
                                   scrape_interval_s=0.05)
        await exporter.start()
        try:
            await exporter._aggregator.scrape_once()
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", exporter.port)
            writer.write(b"GET /metrics HTTP/1.1\r\n\r\n")
            await writer.drain()
            raw = await reader.read(262144)
            writer.close()
        finally:
            await exporter.stop()
            await wrt.shutdown()
            await ert.shutdown()
        exporter_body = raw.decode()

        # -- the SLO watchdog's registry ---------------------------------
        wd = SloWatchdog(SeriesStore(), [SloSpec(
            name="smoke", series="s", objective=1.0)])
        wd.evaluate(0.0)
        return frontend_body, exporter_body, wd.render()

    return asyncio.run(main())


def test_every_documented_family_renders_on_its_surface(rendered_surfaces):
    frontend, exporter, watchdog = rendered_surfaces
    bodies = {"frontend": [frontend], "exporter": [exporter],
              "both": [frontend, exporter], "watchdog": [watchdog]}
    missing = []
    for fam, surface in sorted(parse_catalog().items()):
        for body in bodies[surface]:
            if (f"# HELP {fam} " not in body
                    or f"# TYPE {fam} " not in body):
                missing.append((fam, surface))
                break
    assert not missing, (
        f"{len(missing)} documented famil(ies) missing HELP/TYPE on "
        f"their surface: {missing[:10]}")


def test_dynamic_series_prove_the_planes_are_plumbed(rendered_surfaces):
    """Beyond HELP/TYPE presence: the aggregated request and the disagg
    request must have left visible values — the regressions this
    catches are render folds silently dropping a stats source."""
    frontend, exporter, _ = rendered_surfaces
    assert re.search(r'llm_http_service_requests_total{[^}]*'
                     r'request_type="unary"[^}]*} 1', frontend)
    # the disagg request shipped KV pages through the transfer layer
    m = re.search(r"^llm_kv_transfer_fetches (\d+)", frontend, re.M)
    assert m and int(m.group(1)) >= 1
    # the ledger fold saw real engine steps (ledger is on by default)
    m = re.search(r"^llm_engine_steps_total (\d+)", frontend, re.M)
    assert m and int(m.group(1)) >= 1
    # the exporter scraped a live worker into labeled series
    assert 'llm_kv_blocks_active{worker="w0"} 2' in exporter
    assert re.search(r"^llm_workers 1", exporter, re.M)