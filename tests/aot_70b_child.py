"""AOT-compile the llama3-70b scale-out plan on a virtual pp4 x tp4 mesh
and report per-device compiled memory (spawned by test_70b_memory.py with
xla_force_host_platform_device_count=16; prints one JSON line).

No arrays are ever materialized: params/cache enter as ShapeDtypeStructs
via jax.eval_shape and the decode window + a prefill chunk are lowered and
compiled AOT. XLA's CompiledMemoryStats is per-device under SPMD, so the
numbers are the HBM a real v5e chip would need for this plan.
"""
import functools
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.engine.config import get_model_config  # noqa: E402
from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.llama import AttnMetadata  # noqa: E402
from dynamo_tpu.models.pp import pp_decode_window, pp_forward  # noqa: E402
from dynamo_tpu.parallel.mesh import make_mesh  # noqa: E402


def per_device_mem(compiled) -> dict:
    ma = compiled.memory_analysis()
    # resident: what must LIVE on the device across steps — sharded params
    # + cache + step I/O, net of donation aliasing (cache updated in
    # place). This is the cross-platform invariant: a sharding regression
    # (e.g. layers silently replicated) multiplies it 4-16x. temp is
    # reported for information only: the CPU backend materializes layout
    # copies of the scanned weight stacks that the TPU compiler fuses, so
    # CPU temp wildly overstates TPU workspace.
    return {
        "resident": (ma.argument_size_in_bytes + ma.output_size_in_bytes
                     - ma.alias_size_in_bytes),
        "temp_cpu": ma.temp_size_in_bytes,
    }


def main():
    import dataclasses

    pp, tp = 4, 4
    cfg = get_model_config("llama3-70b")  # bf16, 80 layers
    if "--int8" in sys.argv:
        # weight-only int8 (ops/quant.py): the dense projections become
        # int8 + scales, roughly halving resident weight bytes — the
        # 70B-on-fewer-chips story. pp2 x tp4 = 8 devices.
        cfg = dataclasses.replace(cfg, quant="int8")
        pp = 2
    mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])

    # serving shapes: 8 slots x 2048-token contexts, page 64
    slots, page_size, ctx = 8, 64, 2048
    num_pages = slots * ctx // page_size
    pages_per_seq = ctx // page_size
    n_steps = 8  # scan length; pp window memory is step-count-invariant

    from dynamo_tpu.ops.quant import quantize_params

    def make_params(k):
        p = llama.init_params(k, cfg)
        return quantize_params(p, cfg) if cfg.quant == "int8" else p

    params = jax.eval_shape(make_params, jax.random.PRNGKey(0))
    cache = jax.eval_shape(lambda: llama.init_cache(cfg, num_pages,
                                                    page_size))
    param_bytes = sum(np.prod(x.shape) * x.dtype.itemsize
                      for x in jax.tree.leaves(params))

    sds = jax.ShapeDtypeStruct
    dec = jax.jit(
        functools.partial(pp_decode_window, cfg, (128001,), mesh, n_steps,
                          page_size, True, False),
        donate_argnums=(1,)).lower(
        params, cache,
        sds((slots,), jnp.int32), sds((slots,), jnp.int32),
        sds((slots, pages_per_seq), jnp.int32), sds((slots,), jnp.int32),
        sds((slots,), jnp.int32), sds((slots,), jnp.int32),
        sds((slots,), bool), sds((slots, 2), jnp.int32),
        sds((slots,), jnp.float32), sds((slots,), jnp.int32),
        sds((slots,), jnp.float32), sds((slots,), jnp.int32)).compile()
    dec_mem = per_device_mem(dec)

    # batched prefill chunk (the other big live set): 8 x 128 tokens
    chunk = 128
    pf = jax.jit(
        lambda p, c, t, pos, pt, kl, wi: pp_forward(
            p, cfg, t, c,
            AttnMetadata(positions=pos, page_table=pt, kv_lens=kl,
                         write_idx=wi), mesh)[1],
        donate_argnums=(1,)).lower(
        params, cache, sds((slots, chunk), jnp.int32),
        sds((slots, chunk), jnp.int32),
        sds((slots, pages_per_seq), jnp.int32),
        sds((slots,), jnp.int32),
        sds((slots, chunk), jnp.int32)).compile()
    pf_mem = per_device_mem(pf)

    print(json.dumps({
        "mesh": f"pp{pp}xtp{tp}",
        "param_bytes_total": int(param_bytes),
        "decode": dec_mem,
        "prefill": pf_mem,
    }))


if __name__ == "__main__":
    main()
