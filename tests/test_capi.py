"""C-API binding tests: a native (C++) worker publishing KV events into the
live control plane, received by the Python router side.

Parity target: the reference's C bindings let C++ executor threads emit KV
events into the runtime (reference: lib/bindings/c/src/lib.rs:52-297); here
libcapi.so speaks the framework's own wire protocol to a real
ControlPlaneServer over TCP and the event lands in the same
`{ns}.{component}.kv_events` subject KvIndexer consumes.
"""
import asyncio

import pytest

from dynamo_tpu.engine.kv_cache import tokens_hash
from dynamo_tpu.kv_router.protocols import (KvCacheRemoveData,
                                            KvCacheStoreData, RouterEvent)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.server import ControlPlaneServer


@pytest.fixture(scope="module")
def capi():
    from dynamo_tpu.native.capi_py import CApi
    try:
        return CApi()
    except RuntimeError as e:
        pytest.skip(f"native capi unavailable: {e}")


def test_tokens_hash_matches_python(capi):
    """The C hash must equal engine/kv_cache.tokens_hash id-for-id — a
    mismatch would silently break routing for native workers (same recipe
    as reference indexer.rs:87-104, xxh3_64 seed 1337 over LE32 bytes)."""
    for toks in ([], [0], [1, 2, 3], list(range(16)),
                 [7, 2**31 - 1, 42] * 21):
        assert capi.tokens_hash(toks) == tokens_hash(toks), toks


def test_publish_stored_and_removed_end_to_end(capi, tmp_path):
    async def main():
        server = await ControlPlaneServer(
            port=0, data_dir=str(tmp_path / "cp")).start()
        try:
            rt = await DistributedRuntime.connect(
                "127.0.0.1", server.port, "pysub")
            sub = await rt.namespace("ns").component("engine").subscribe(
                "kv_events")

            page = 16
            blk = [(0xdead0001, list(range(page))),
                   (0xdead0002, list(range(page, 2 * page)))]
            # >15 blocks exercises the msgpack array16 path; >255-byte
            # payload exercises bin16 framing
            many = [(0xbeef0000 + i, [i] * page) for i in range(20)]

            def native_calls():
                capi.init("ns", "engine", "w-native", page,
                          "127.0.0.1", server.port)
                capi.publish_stored(1, None, blk)
                # partial pages are refused at the ABI edge WHILE connected
                # (engine/kv_cache.py indexes only full pages) — checked
                # here, mid-session, so the error demonstrably comes from
                # the page-size validation and not the closed-socket guard
                try:
                    capi.publish_stored(9, None, [(1, [1, 2, 3])])
                except IOError:
                    pass
                else:
                    raise AssertionError("partial page was not refused")
                capi.publish_stored(2, blk[-1][0], many)
                capi.publish_removed(3, [bh for bh, _ in many])
                capi.shutdown()

            await asyncio.wait_for(asyncio.to_thread(native_calls), 30)

            events = []
            async def drain():
                async for _subj, payload in sub:
                    events.append(RouterEvent.unpack(payload))
                    if len(events) == 3:
                        return
            await asyncio.wait_for(drain(), 10)

            ev1, ev2, ev3 = events
            assert [e.event.event_id for e in events] == [1, 2, 3]
            assert all(e.worker_id == "w-native" for e in events)

            d1 = ev1.event.data
            assert isinstance(d1, KvCacheStoreData)
            assert d1.parent_hash is None
            assert [(b.block_hash, b.tokens_hash) for b in d1.blocks] == \
                [(bh, tokens_hash(toks)) for bh, toks in blk]

            d2 = ev2.event.data
            assert d2.parent_hash == blk[-1][0]
            assert len(d2.blocks) == 20
            assert d2.blocks[7].tokens_hash == tokens_hash([7] * page)

            d3 = ev3.event.data
            assert isinstance(d3, KvCacheRemoveData)
            assert d3.block_hashes == [bh for bh, _ in many]

            await rt.shutdown()
        finally:
            await server.stop()

    asyncio.run(main())


def test_uninitialized_calls_fail_fast(capi):
    """After shutdown (or before init) every publish fails with an error,
    not a hang or a crash."""
    with pytest.raises(IOError):
        capi.publish_removed(1, [1, 2])
