"""Control-plane scale harness (runtime/simcluster.py): the tier-1 smoke
plus the slow full-scale run.

The smoke is the CI shape of the 1000-worker sim: 64 mock workers, one
seeded rolling-restart storm under schedule load, a watch-disconnect
burst, and an event-plane lag storm that must round-trip the router's
stale-snapshot degraded mode. Contracts: zero scheduling errors, zero
post-fence picks (the router never selects a dead/draining worker after
its watch event is applied), watcher convergence, degraded in AND out.
The full `--workers 1000` run stays behind `-m slow` and the TPU watch
ladder (`tools/cluster_sim.py` commits SCALE_r07.json).
"""
import asyncio

import pytest

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.cpstats import CP_STATS
from dynamo_tpu.runtime.simcluster import (
    SimCluster, SimConfig, family_tokens, percentile, pick_storm_targets,
)


@pytest.fixture(autouse=True)
def clean_cp_state():
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()
    CP_STATS.reset()
    yield
    faults.REGISTRY.disarm()
    faults.REGISTRY.reset_counters()
    CP_STATS.reset()


def test_storm_targets_are_a_pure_function_of_seed():
    ids = [f"w{i:04d}" for i in range(100)]
    a = pick_storm_targets(42, ids, 0.3)
    b = pick_storm_targets(42, list(reversed(ids)), 0.3)
    assert a == b and len(a) == 30
    assert pick_storm_targets(43, ids, 0.3) != a


def test_family_tokens_deterministic_and_distinct():
    assert family_tokens(3, 16, 4) == family_tokens(3, 16, 4)
    assert family_tokens(3, 16, 4) != family_tokens(4, 16, 4)


def test_percentile_edges():
    assert percentile([], 0.99) == 0.0
    assert percentile([1.0], 0.5) == 1.0
    # nearest-rank: 0.99 * (n-1) rounds to index 98 of 0..99
    assert percentile(list(map(float, range(100))), 0.99) == 98.0


def test_cluster_sim_smoke_64_workers_storms_hold_contracts(tmp_path):
    """The tier-1 sim smoke: seeded, deterministic storm membership,
    every routing contract enforced end to end — with trace capture on
    (tools/cluster_sim.py --trace path): every schedule decision during
    the storms lands as a router-scope span, exported via
    tools/artifacts.py into a chrome-loadable artifact."""
    from dynamo_tpu.runtime.tracing import TRACER, chrome_trace
    TRACER.configure(enabled=True, sample_rate=1.0)
    TRACER.drain()

    async def main():
        sim = await SimCluster(SimConfig(
            workers=64, streams=512, seed=11, lease_ttl_s=2.0,
            scrape_interval_s=0.1, degraded_lag_s=0.5)).start()
        try:
            # steady-state load over shared-prefix streams
            load = await sim.run_load(400)
            assert load["calls"] == 400
            assert sim.schedule_errors == 0 and sim.dead_picks == 0
            # prefix overlap actually drives routing (radix index live)
            assert sim.router.indexer.num_nodes() > 0

            # storm 1: seeded rolling restart under load — zero errors,
            # and never a post-fence pick
            rr = await sim.storm_rolling_restart(fraction=0.25,
                                                 load_calls=300)
            assert rr["errors"] == 0 and rr["dead_picks"] == 0
            assert rr["targets"] == 16
            assert len(sim.client.instances) == 64   # fleet recovered

            # storm 2: watch-stream disconnect burst — the client pump
            # resumes with backoff and RESYNCS (no silent dead watcher)
            wd = await sim.storm_watch_disconnect(kills=2, load_calls=100)
            assert wd["converged"], wd
            assert wd["resyncs"] >= 1
            assert wd["errors"] == 0 and wd["dead_picks"] == 0

            # storm 3: event-plane lag — degraded mode in AND out, with
            # scheduling uninterrupted and the flag on CP_STATS
            lag = await sim.storm_event_lag(delay_s=1.0, load_calls=100)
            assert lag["entered"] and lag["exited"], lag
            assert lag["errors"] == 0 and lag["dead_picks"] == 0
            assert CP_STATS.router_degraded == 0
            assert CP_STATS.router_degraded_entries >= 1

            summary = sim.summary()
            assert summary["schedule_errors"] == 0
            assert summary["dead_picks"] == 0
            assert summary["p99_us"] > 0
            return summary
        finally:
            await sim.stop()

    try:
        summary = asyncio.run(asyncio.wait_for(main(), 120))
        # storm trace capture: one span per schedule decision, written
        # through the evidence policy, chrome twin loadable
        import json

        from tools.artifacts import append_jsonl, write_json
        spans = TRACER.drain()
        sched = [s for s in spans if s["name"] == "router.schedule"]
        assert len(sched) == summary["schedule_calls"]
        assert all(s["trace_id"] == "scope:router" for s in sched)
        assert any("instance" in (s["attrs"] or {}) for s in sched)
        out = str(tmp_path / "scale_trace.jsonl")
        for s in spans:
            append_jsonl(out, s)
        write_json(out + ".chrome.json", chrome_trace(spans),
                   overwrite=True)
        with open(out + ".chrome.json") as f:
            assert json.load(f)["traceEvents"]
    finally:
        TRACER.configure(enabled=False)
        TRACER.drain()


def test_lease_expiry_burst_prunes_then_recovers():
    """A heartbeat blackout for a seeded fraction expires their leases in
    one burst (mass watch-delete flood, coalesced by the batched pump);
    jittered re-registration restores the fleet without a stampede."""
    async def main():
        sim = await SimCluster(SimConfig(
            workers=32, streams=128, seed=3, lease_ttl_s=1.0,
            scrape_interval_s=0.1)).start()
        try:
            le = await sim.storm_lease_expiry(fraction=0.25, load_calls=100)
            assert le["expired"] == le["targets"] == 8
            assert le["errors"] == 0 and le["dead_picks"] == 0
            assert len(sim.client.instances) == 32
        finally:
            await sim.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_routing_ab_transfer_aware_beats_prefix_only_p99():
    """ISSUE 11 acceptance: over a fleet with seeded heterogeneous link
    speeds (two-decade bandwidth ladder + per-link seeded delay-fault
    schedules), transfer-aware scoring improves p99 simulated TTFT over
    prefix-overlap-only scoring, routes fewer byte-heavy requests onto
    slow links, and the whole report is a pure function of the seed
    (same seed -> identical dict, the ROUTING_AB_r11.json contract)."""
    async def run(seed):
        sim = await SimCluster(SimConfig(
            workers=48, streams=256, seed=seed)).start()
        try:
            return await sim.routing_ab(requests=800)
        finally:
            await sim.stop()

    report = asyncio.run(asyncio.wait_for(run(11), 120))
    assert report["transfer_aware"]["ttft_p99_ms"] \
        < report["prefix_only"]["ttft_p99_ms"]
    # a real margin, not a rounding fluke (seeds 0/3/7/11/42 all land
    # 6-11% at this scale)
    assert report["p99_improvement"] > 0.02
    # cold links existed and were scored (fleet-median fallback in anger)
    assert report["cold_links"] > 0
    assert report["measured_links"] > 0
    # seeded-replayable: the committed artifact can be regenerated
    report2 = asyncio.run(asyncio.wait_for(run(11), 120))
    assert report == report2


@pytest.mark.slow
def test_cluster_sim_full_scale_1000_workers():
    """The full-scale run (the committed SCALE_r07.json shape): behind
    -m slow; tools/cluster_sim.py is the artifact-committing driver."""
    async def main():
        sim = await SimCluster(SimConfig(
            workers=1000, streams=20_000, seed=7)).start()
        try:
            await sim.run_load(2000)
            rr = await sim.storm_rolling_restart(fraction=0.3,
                                                 load_calls=2000)
            assert rr["errors"] == 0 and rr["dead_picks"] == 0
            lag = await sim.storm_event_lag(delay_s=1.5, load_calls=500)
            assert lag["entered"] and lag["exited"]
            assert sim.summary()["schedule_errors"] == 0
        finally:
            await sim.stop()

    asyncio.run(asyncio.wait_for(main(), 600))


def test_re_role_fence_under_churning_load():
    """ISSUE 12 satellite: a worker re-registering under a new role is
    never schedulable for its OLD role between the draining fence and
    the new-role re-put. Roles churn continuously under role-filtered
    scheduling load; `re_role_worker` asserts the fence at both edges
    (after the draining event applies, and after the new-role
    registration) and the load task cross-checks every pick's live
    role against the watch-applied instance info."""
    from dynamo_tpu.runtime.autoscaler import ROLE_DECODE, ROLE_PREFILL

    async def main():
        sim = await SimCluster(SimConfig(workers=16, streams=64,
                                         lease_ttl_s=30.0,
                                         seed=9)).start()
        try:
            ids = sorted(sim.workers)
            for i, wid in enumerate(ids):
                await sim.workers[wid].assign_role(
                    ROLE_PREFILL if i < 8 else ROLE_DECODE)
            # wait for the roles to land on the watch
            deadline = asyncio.get_running_loop().time() + 5.0
            while len(sim.client.ids_for_role(ROLE_PREFILL)) != 8:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)

            stop = asyncio.Event()
            mismatches = 0

            async def load():
                nonlocal mismatches
                while not stop.is_set():
                    for role in (ROLE_PREFILL, ROLE_DECODE):
                        for pick in sim.client.ids_for_role(role):
                            info = sim.client.instances.get(pick)
                            # the fence contract: a listed pick's
                            # APPLIED info serves that role (or is a
                            # role-less wildcard) and is not draining
                            if info is None or (
                                    info.get("role") not in (role, None)
                                    or info.get("status") == "draining"):
                                mismatches += 1
                    await asyncio.sleep(0)

            load_task = asyncio.create_task(load())
            violations = 0
            # churn: flip 6 workers decode->prefill->decode twice over
            for _round in range(2):
                for wid in ids[8:14]:
                    violations += await sim.re_role_worker(
                        wid, ROLE_PREFILL, old_role=ROLE_DECODE)
                for wid in ids[8:14]:
                    violations += await sim.re_role_worker(
                        wid, ROLE_DECODE, old_role=ROLE_PREFILL)
            stop.set()
            await load_task
            return violations, mismatches, sim
        finally:
            await sim.stop()

    violations, mismatches, sim = asyncio.run(
        asyncio.wait_for(main(), 60))
    assert violations == 0
    assert mismatches == 0
