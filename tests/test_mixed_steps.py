"""Fused prefill+decode steps (MixedPlan, docs/PERF.md).

Exactness bar: with the mixed-step scheduler ON (the default), greedy
AND seeded-sampled streams must be TOKEN-IDENTICAL to the legacy
alternating scheduler, with requests admitted mid-stream, at every
pipeline depth. Anti-stall bar: while a long prompt prefills, a running
stream's next token is never delayed by more than one mixed step, and
decode_stall_steps stays 0 (the alternating baseline pays > 0).

Engines are module-scoped and reused across tests (engine rebuilds
recompile every jitted program — the tier-1 budget is tight), and the
alternating ORACLE is the same engine with its runtime-flippable
`scheduler.mixed_token_budget` set to 0, so no third engine build is
paid; scheduler-level tests construct bare Schedulers and cost no
compiles at all.
"""
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import (
    DecodePlan, EngineRequest, MixedPlan, PrefillPlan, SamplingParams,
    Scheduler, next_bucket,
)

CFG = ModelConfig(dtype="float32", max_model_len=512)

ENGINE_KW = dict(
    page_size=16, num_pages=64, max_slots=2, max_prefill_chunk=32,
    prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4)


def make_engine(depth, budget, **kw):
    defaults = dict(ENGINE_KW, pipeline_depth=depth,
                    mixed_token_budget=budget)
    defaults.update(kw)
    return NativeEngine(CFG, EngineConfig(**defaults), seed=0)


@pytest.fixture(scope="module")
def eng_mixed():
    return make_engine(1, 512)


@pytest.fixture(scope="module")
def eng_mixed_pipe():
    return make_engine(2, 512)


def drive_alternating(eng, tag, params, prompts):
    """Reference drive: legacy alternating scheduler on the SAME engine
    (budget flipped to 0 for the drive, restored after)."""
    budget = eng.scheduler.mixed_token_budget
    eng.scheduler.mixed_token_budget = 0
    try:
        return drive_with_admissions(eng, tag, params, prompts)
    finally:
        eng.scheduler.mixed_token_budget = budget


def drive_with_admissions(eng, tag, params, prompts):
    """Run 3 requests with B admitted after A streams 2 tokens and C
    after B's first token — admissions land mid-decode, so the mixed
    engines take fused steps (and the pipelined engine must drain +
    re-prime around them)."""
    got = {f"{tag}A": []}
    eng.add_request(EngineRequest(f"{tag}A", prompts[0], params[0]))
    done, added_b, added_c = set(), False, False
    steps = 0
    while len(done) < 3 and steps < 400:
        steps += 1
        for ev in eng.step():
            if ev.token is not None:
                got[ev.request_id].append(ev.token)
            if ev.finished:
                done.add(ev.request_id)
        if not added_b and len(got[f"{tag}A"]) >= 2:
            got[f"{tag}B"] = []
            eng.add_request(EngineRequest(f"{tag}B", prompts[1], params[1]))
            added_b = True
        if added_b and not added_c and got[f"{tag}B"]:
            got[f"{tag}C"] = []
            eng.add_request(EngineRequest(f"{tag}C", prompts[2], params[2]))
            added_c = True
    assert len(done) == 3, (sorted(done), steps)
    return [got[f"{tag}{x}"] for x in "ABC"]


# B is multi-chunk (68 > max_prefill_chunk=32: 3 chunks) so admissions
# land mid-decode across several fused steps; kept short — every extra
# chunk is tier-1 budget
PROMPTS = [list(range(3, 19)), list(range(40, 108)), list(range(200, 210))]


def test_mixed_token_identity_every_depth_greedy(eng_mixed,
                                                 eng_mixed_pipe):
    """Pipeline x admission interaction: requests admitted mid-stream at
    depth 1 and depth 2 with mixed steps on produce streams token-equal
    to the alternating synchronous loop."""
    greedy = [
        SamplingParams(max_tokens=14, temperature=0.0, ignore_eos=True),
        SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True),
        SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)]
    m0 = eng_mixed.mixed_steps
    ref = drive_alternating(eng_mixed, "idgr", greedy, PROMPTS)
    mix = drive_with_admissions(eng_mixed, "idgm", greedy, PROMPTS)
    pipe = drive_with_admissions(eng_mixed_pipe, "idgp", greedy, PROMPTS)
    assert mix == ref
    assert pipe == ref
    assert eng_mixed.mixed_steps > m0  # fused steps actually ran


def test_mixed_token_identity_seeded_sampled(eng_mixed_pipe):
    """Seeded-sampled streams (temperature/top-k/top-p) under mid-stream
    admissions: mixed + pipelined must equal the alternating reference
    token-for-token — same per-request (seed, counter) keys through the
    shared sample_logits tail. One engine carries both drives (the
    sampled program variants are the expensive compiles)."""
    sampled = [
        SamplingParams(max_tokens=10, temperature=0.9, top_k=12, seed=7,
                       ignore_eos=True),
        SamplingParams(max_tokens=8, temperature=0.7, top_p=0.8, seed=3,
                       ignore_eos=True),
        SamplingParams(max_tokens=6, temperature=0.8, seed=11,
                       ignore_eos=True)]
    ref = drive_alternating(eng_mixed_pipe, "idsr", sampled, PROMPTS)
    mix = drive_with_admissions(eng_mixed_pipe, "idsm", sampled, PROMPTS)
    assert mix == ref


def test_long_prompt_never_stalls_running_stream(eng_mixed):
    """Starvation bound: while a multi-chunk prompt prefills, the
    already-running stream emits a token on EVERY engine step — a long
    arrival delays a running stream's next token by at most one mixed
    step (the alternating scheduler stalled it for whole prefill
    steps)."""
    eng = eng_mixed
    p_run = SamplingParams(max_tokens=16, temperature=0.0, ignore_eos=True)
    p_new = SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True)
    eng.add_request(EngineRequest("starveA", list(range(5, 21)), p_run))
    tokens_a = 0
    while tokens_a < 2:  # A is decoding
        tokens_a += sum(1 for ev in eng.step()
                        if ev.token is not None
                        and ev.request_id == "starveA")
    stall0 = eng.decode_stall_steps
    eng.add_request(EngineRequest("starveB", list(range(50, 118)), p_new))
    # drive until B finishes; every step that did work must include an
    # "starveA" token while A is still live
    a_done = b_done = False
    while not (a_done and b_done):
        evs = eng.step()
        a_toks = sum(1 for ev in evs if ev.token is not None
                     and ev.request_id == "starveA")
        for ev in evs:
            if ev.finished and ev.request_id == "starveA":
                a_done = True
            if ev.finished and ev.request_id == "starveB":
                b_done = True
        if evs and not a_done:
            assert a_toks >= 1, "running stream skipped a step"
    assert eng.decode_stall_steps == stall0  # zero stall steps throughout


def test_alternating_baseline_counts_stall_steps(eng_mixed):
    """The stall counter attributes the interference the mixed scheduler
    removes: under the legacy policy (budget flipped to 0), prefill
    chunks that run while a decode is live each count one
    decode_stall_step."""
    eng = eng_mixed
    eng.scheduler.mixed_token_budget = 0
    try:
        p_run = SamplingParams(max_tokens=16, temperature=0.0,
                               ignore_eos=True)
        p_new = SamplingParams(max_tokens=4, temperature=0.0,
                               ignore_eos=True)
        eng.add_request(EngineRequest("stallA", list(range(5, 21)), p_run))
        got = 0
        while got < 2:
            got += sum(1 for ev in eng.step() if ev.token is not None)
        stall0 = eng.decode_stall_steps
        eng.add_request(EngineRequest("stallB", list(range(50, 118)),
                                      p_new))
        while eng.has_work():
            eng.step()
        assert eng.decode_stall_steps > stall0
    finally:
        eng.scheduler.mixed_token_budget = eng.cfg.mixed_token_budget


def test_metrics_carry_mixed_and_stall_counters(eng_mixed):
    m = eng_mixed.metrics()
    assert m.mixed_steps == eng_mixed.mixed_steps > 0
    assert m.decode_stall_steps == eng_mixed.decode_stall_steps
    # wire path keeps them (the /metrics exporter's source)
    import dataclasses

    from dynamo_tpu.kv_router.scoring import WorkerMetrics
    w = WorkerMetrics.from_dict(dataclasses.asdict(m))
    assert w.mixed_steps == m.mixed_steps
    assert w.decode_stall_steps == m.decode_stall_steps


# -- scheduler-level (no jit, no compiles) ------------------------------------


def sched(**kw):
    defaults = dict(page_size=8, num_pages=128, max_slots=2,
                    max_prefill_chunk=8, prefill_buckets=(8,),
                    max_model_len=512)
    defaults.update(kw)
    return Scheduler(EngineConfig(**defaults))


def commit_any(s, plan):
    """Drive a scheduler plan to completion host-side (no device)."""
    if isinstance(plan, MixedPlan):
        for i, seq in enumerate(plan.seqs):
            if seq is not None and plan.is_decode[i]:
                s.commit_decode_token(seq, 1)
        for i in reversed(range(len(plan.seqs))):
            seq = plan.seqs[i]
            if seq is None or plan.is_decode[i]:
                continue
            s.commit_prefill_row(plan, i,
                                 9 if plan.is_last_chunk[i] else None)
    elif isinstance(plan, PrefillPlan):
        for i in reversed(range(len(plan.seqs))):
            s.commit_prefill_row(plan, i,
                                 9 if plan.is_last_chunk[i] else None)
    else:
        s.commit_decode(plan, np.zeros(s.cfg.max_slots, np.int64))


def test_mixed_plan_layout_and_budget():
    """Decode rows lead the plan as one-token causal rows; every row is
    charged the full token bucket: Tb * (rows) <= mixed_token_budget,
    and all leading dims are bucketed."""
    s = sched(mixed_token_budget=32)
    s.add_request(EngineRequest("a", list(range(2, 10)),
                                SamplingParams(max_tokens=50,
                                               ignore_eos=True)))
    s.commit_prefill(s.schedule(), 7)  # a takes a decode slot
    s.add_request(EngineRequest("b", list(range(100, 180)),
                                SamplingParams(max_tokens=4,
                                               ignore_eos=True)))
    plan = s.schedule()
    assert isinstance(plan, MixedPlan)
    tb = plan.tokens.shape[1]
    assert tb in s.prefill_buckets
    n_rows = sum(1 for q in plan.seqs if q is not None)
    assert tb * n_rows <= 32
    # decode row: a's last token at column 0, kv_lens = position + 1
    i = plan.is_decode.index(True)
    a = plan.seqs[i]
    assert a.request_id == "a"
    assert plan.tokens[i, 0] == a.output[-1]
    assert plan.kv_lens[i] == a.total_len
    assert plan.last_idx[i] == 0
    assert plan.write_idx[i, 0] >= 0 and np.all(plan.write_idx[i, 1:] < 0)
    # prefill row rides the same step
    j = next(k for k, q in enumerate(plan.seqs)
             if q is not None and not plan.is_decode[k])
    assert plan.seqs[j].request_id == "b"
    # batch dim sits on the fixed pow2 ladder
    assert plan.tokens.shape[0] & (plan.tokens.shape[0] - 1) == 0


def test_streak_retired_decode_rides_every_step():
    """With mixed steps on, a multi-chunk prompt admitted against a
    running decode yields ONLY MixedPlans until its prefill completes —
    no pure-prefill stall steps, no streak bookkeeping."""
    s = sched(mixed_token_budget=32)
    s.add_request(EngineRequest("a", list(range(2, 10)),
                                SamplingParams(max_tokens=60,
                                               ignore_eos=True)))
    s.commit_prefill(s.schedule(), 7)
    s.add_request(EngineRequest("b", list(range(100, 180)),
                                SamplingParams(max_tokens=4,
                                               ignore_eos=True)))
    kinds = ""
    for _ in range(14):
        plan = s.schedule()
        if plan is None:
            break
        kinds += ("m" if isinstance(plan, MixedPlan) else
                  "p" if isinstance(plan, PrefillPlan) else "d")
        commit_any(s, plan)
    # b is 80 tokens -> 10 chunks of 8, every one fused with a's decode
    assert kinds.startswith("m" * 10), kinds
    assert "p" not in kinds, kinds


def test_prefill_skip_ahead_unblocks_later_request():
    """Head-of-line fix: a head whose FINAL chunk needs a decode slot
    (none free) no longer blocks a later multi-chunk request that could
    run now; with skip-ahead disabled the old blocking behavior is
    preserved."""
    def setup(skip):
        s = sched(max_slots=1, prefill_skip_ahead=skip,
                  mixed_token_budget=0)
        # fill the only slot
        s.add_request(EngineRequest("run", list(range(2, 10)),
                                    SamplingParams(max_tokens=60,
                                                   ignore_eos=True)))
        s.commit_prefill(s.schedule(), 7)
        # head: single-chunk prompt whose final chunk needs a slot -> blocked
        s.add_request(EngineRequest("head", list(range(20, 28)),
                                    SamplingParams(max_tokens=4)))
        # later: an 80-token prompt with chunks to burn before needing one
        s.add_request(EngineRequest("later", list(range(100, 180)),
                                    SamplingParams(max_tokens=4)))
        return s

    s = setup(skip=4)
    plan = s._schedule_prefill()
    assert plan is not None
    assert plan.seq.request_id == "later"
    # queue order preserved: head still first in line
    assert s.waiting[0].request_id == "head"

    s = setup(skip=0)
    assert s._schedule_prefill() is None  # old head-of-line behavior


def test_skip_ahead_memory_dead_end_still_raises():
    """Skip-ahead must not swallow the true dead end: a prompt that can
    never fit raises MemoryError when nothing can free pages."""
    s = sched(num_pages=4, max_prefill_chunk=8, prefill_skip_ahead=4)
    # 40-token prompt, 4 pages x 8 = 32 token slots: the 5th chunk can
    # never get a page
    s.add_request(EngineRequest("big", list(range(2, 42)),
                                SamplingParams(max_tokens=4)))
    with pytest.raises(MemoryError):
        for _ in range(8):
            plan = s.schedule()
            assert plan is not None
            commit_any(s, plan)


def test_mixed_page_width_uses_admission_bucket():
    """A mixed plan's page-table width covers each decode row's
    ADMISSION-TIME allocation (prompt + max_tokens), so the width never
    moves mid-request and mixed steps reuse compiled programs across a
    request's whole life (dynalint R10's invariant)."""
    s = sched(mixed_token_budget=32)
    s.add_request(EngineRequest("a", list(range(2, 10)),
                                SamplingParams(max_tokens=100,
                                               ignore_eos=True)))
    s.commit_prefill(s.schedule(), 7)
    s.add_request(EngineRequest("b", list(range(100, 140)),
                                SamplingParams(max_tokens=4,
                                               ignore_eos=True)))
    plan = s.schedule()
    assert isinstance(plan, MixedPlan)
    ps = s.cfg.page_size
    need = -(-(8 + 100) // ps)  # a's admission-time page need
    assert plan.page_table.shape[1] >= next_bucket(need, s.page_buckets)
