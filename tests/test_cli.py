"""CLI smoke tests: dynamo_tpu.run (dynamo-run equivalent) + llmctl.

Reference: launch/dynamo-run opt matrix + llmctl registry ops (SURVEY.md
§2 L4). Subprocess-driven with the echo engine (no hardware, fast).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}


def test_run_batch_echo(tmp_path):
    batch = tmp_path / "b.jsonl"
    batch.write_text('{"prompt": "hello"}\n{"prompt": "again"}\n')
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run",
         f"in=batch:{batch}", "out=echo", "tiny"],
        capture_output=True, text=True, timeout=120, env=ENV, cwd=REPO)
    assert out.returncode == 0, out.stderr
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert len(lines) == 2
    assert "hello" in lines[0]["text"]
    assert lines[0]["finish_reason"] == "stop"


def test_run_stdin_echo():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run", "in=stdin", "out=echo"],
        input="ping pong", capture_output=True, text=True, timeout=120,
        env=ENV, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "ping pong" in out.stdout


def test_run_rejects_unknown_specs():
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run", "in=bogus", "out=echo"],
        capture_output=True, text=True, timeout=120, env=ENV, cwd=REPO)
    assert out.returncode != 0
    assert "unknown in=" in out.stderr


def test_run_builds_pp_tp_mesh_engine():
    """The launcher exposes every mesh axis (reference passes TP/PP to its
    engines via --tensor-parallel-size / node counts — vllm_inc.py:37-38):
    in=none builds the full pp x tp engine on a virtual 8-device mesh and
    exits, proving the flag plumbing end-to-end without hardware."""
    env = {**ENV,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    out = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run", "in=none", "out=native",
         "tiny", "--tp", "2", "--pp", "2", "--num-pages", "32",
         "--max-slots", "4"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "READY (in=none" in out.stdout
