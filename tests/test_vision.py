"""Multimodal (vision) path tests: encoder, engine mm prefill, prefix-cache
salting, chunk-straddling image spans.

The reference serves multimodal via its engines (SURVEY.md §7 stage 7,
BASELINE config #5 Qwen2-VL); here the vision tower is a first-class JAX
encoder (models/vision.py) whose projected patch embeds mix into the text
prefill at placeholder positions (models/llama.forward embeds_mask path).
"""
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig, VisionConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams

VCFG = VisionConfig(image_size=28, patch_size=14, hidden_size=32,
                    intermediate_size=64, num_layers=2, num_heads=2)
CFG = ModelConfig(dtype="float32", max_model_len=256, vision=VCFG)
N_PATCH = 4  # (28/14)^2


def make_engine(**kw):
    cfg = dict(page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=32,
               prefill_buckets=(8, 16, 32), max_model_len=256)
    cfg.update(kw)
    return NativeEngine(CFG, EngineConfig(**cfg), seed=0)


def image(seed):
    rng = np.random.RandomState(seed)
    return rng.rand(28, 28, 3).astype(np.float32)


def mm_request(rid, img_embeds, max_tokens=6, prompt_pad=0):
    """prompt = [text..] [IMG x N_PATCH] [text..pad..]; span at offset 4."""
    prompt = [5, 6, 7, 8] + [0] * N_PATCH + [9, 10, 11, 12] \
        + list(range(20, 20 + prompt_pad))
    return EngineRequest(
        rid, prompt,
        SamplingParams(max_tokens=max_tokens, temperature=0.0,
                       ignore_eos=True),
        mm_spans=[(4, img_embeds)])


def test_encoder_shapes_and_determinism():
    eng = make_engine()
    e1 = eng.encode_image(image(0))
    e2 = eng.encode_image(image(0))
    assert e1.shape == (N_PATCH, CFG.hidden_size)
    np.testing.assert_array_equal(e1, e2)
    batch = eng.encode_image(np.stack([image(0), image(1)]))
    assert batch.shape == (2, N_PATCH, CFG.hidden_size)
    np.testing.assert_allclose(batch[0], e1, rtol=1e-5)


def test_image_content_changes_output():
    eng = make_engine()
    e_a = eng.encode_image(image(1))
    e_b = eng.encode_image(image(2))

    def gen(rid, emb):
        req = mm_request(rid, emb)
        eng.add_request(req)
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.token is not None:
                    out.append(ev.token)
        return out

    toks_a = gen("a", e_a)
    toks_b = gen("b", e_b)
    toks_a2 = gen("a2", e_a)
    assert toks_a == toks_a2, "same image must be deterministic"
    assert toks_a != toks_b, "different image must change generation"


def test_prefix_cache_distinguishes_images():
    """Identical placeholder prompts with DIFFERENT images must not alias
    KV pages: admission salts the placeholder ids with the image content
    hash, so their page hashes differ."""
    eng = make_engine()
    e_a = eng.encode_image(image(1))
    e_b = eng.encode_image(image(2))
    s_a = eng.scheduler._admit(mm_request("pa", e_a))
    s_b = eng.scheduler._admit(mm_request("pb", e_b))
    s_a2 = eng.scheduler._admit(mm_request("pa2", e_a))
    assert s_a.prompt[4:4 + N_PATCH] != s_b.prompt[4:4 + N_PATCH]
    assert s_a.prompt == s_a2.prompt  # same image -> same salts (cacheable)
    assert s_a.prompt[:4] == s_b.prompt[:4] == [5, 6, 7, 8]
    for rid in ("pa", "pb", "pa2"):
        eng.scheduler.params.pop(rid, None)


def test_preprocessor_image_parts():
    """Chat image content parts become placeholder ids + ImageParts with
    correct offsets; text around them tokenizes normally. (The round-2
    preprocessor silently dropped non-text parts, VERDICT r2 missing #3.)"""
    import base64
    import io

    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import (
        IMAGE_PLACEHOLDER_ID, OpenAIPreprocessor,
    )
    from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatMessage

    card = ModelDeploymentCard(name="vl", arch="tiny-vl", context_length=256)
    pre = OpenAIPreprocessor(card)

    buf = io.BytesIO()
    np.save(buf, image(7))
    url = "data:application/x-npy;base64," + base64.b64encode(
        buf.getvalue()).decode()
    req = ChatCompletionRequest(
        model="vl", max_tokens=4,
        messages=[ChatMessage(role="user", content=[
            {"type": "text", "text": "what is "},
            {"type": "image_url", "image_url": {"url": url}},
            {"type": "text", "text": "?"},
        ])])
    out, _ = pre.preprocess_chat(req, "rid")
    assert out.mm_parts is not None and len(out.mm_parts) == 1
    part = out.mm_parts[0]
    assert part.shape == [28, 28, 3]
    off = part.offset
    assert out.token_ids[off:off + N_PATCH] == [IMAGE_PLACEHOLDER_ID] * N_PATCH
    # the text before the image tokenizes to the prefix ending at the offset
    prefix = pre.tokenizer.encode("<|user|>what is ")
    assert out.token_ids[:off] == prefix
    # pixel bytes round-trip
    px = np.frombuffer(part.data, np.float32).reshape(part.shape)
    np.testing.assert_array_equal(px, image(7))

    # text-only model must reject image parts
    card_txt = ModelDeploymentCard(name="t", arch="tiny")
    import pytest
    with pytest.raises(ValueError, match="text-only"):
        OpenAIPreprocessor(card_txt).preprocess_chat(req)


def test_multimodal_worker_roundtrip():
    """PreprocessedRequest with mm_parts through NativeEngineWorker: the
    worker decodes pixels, the engine encodes + mixes embeds; output matches
    the direct engine path byte-for-byte."""
    import asyncio

    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.protocols.common import (
        ImagePart, PreprocessedRequest, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    px = image(5)
    eng_direct = make_engine()
    emb = eng_direct.encode_image(px)
    req = mm_request("direct", emb)
    expect = []
    eng_direct.add_request(req)
    while eng_direct.has_work():
        for ev in eng_direct.step():
            if ev.token is not None:
                expect.append(ev.token)

    async def main():
        worker = NativeEngineWorker(make_engine())
        await worker.start()
        try:
            prompt = [5, 6, 7, 8] + [0] * N_PATCH + [9, 10, 11, 12]
            pre = PreprocessedRequest(
                request_id="w", token_ids=prompt,
                stop=StopConditions(max_tokens=6, ignore_eos=True),
                mm_parts=[ImagePart(offset=4, shape=list(px.shape),
                                    data=px.tobytes())])
            toks = []
            async for frame in worker.generate(
                    pre.model_dump(exclude_none=True), Context("w")):
                toks.extend(frame.get("token_ids", ()))
            return toks
        finally:
            await worker.stop()

    assert asyncio.run(main()) == expect


@pytest.mark.parametrize("mm_transfer", ["pixels", "embeds"])
def test_multimodal_disagg_remote_prefill(mm_transfer):
    """Multimodal disaggregation in both transfer modes: "pixels" ships raw
    pixels and the prefill worker re-encodes; "embeds" ships the decode
    tower's output + content salts so the prefill side never runs its
    vision tower (VERDICT r3 weak #6). Either way: KV pages cross the
    transfer plane and tokens match the aggregated engine exactly."""
    import asyncio

    from dynamo_tpu.disagg import (
        DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer,
        PrefillQueue, PrefillWorker, RemoteTransferBackend,
    )
    from dynamo_tpu.llm.worker import NativeEngineWorker
    from dynamo_tpu.protocols.common import (
        ImagePart, PreprocessedRequest, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    px = image(9)
    prompt = [5, 6, 7, 8] + [0] * N_PATCH + list(range(30, 42))
    oracle = make_engine()
    emb = oracle.encode_image(px)
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    oracle.add_request(EngineRequest("o", prompt, params,
                                     mm_spans=[(4, emb)]))
    expect = []
    while oracle.has_work():
        for ev in oracle.step():
            if ev.token is not None:
                expect.append(ev.token)

    async def main():
        plane = MemoryPlane()
        queue = PrefillQueue(plane.messaging, "ns", "tiny-vl")
        router = DisaggregatedRouter(max_local_prefill_length=4,
                                     max_prefill_queue_size=8,
                                     model="tiny-vl")
        decode = DisaggDecodeWorker(
            make_engine(), plane.messaging, router, queue,
            worker_id="dec-vl", prefill_timeout_s=60.0,
            mm_transfer=mm_transfer)
        server = await KvTransferServer(decode, "dec-vl").start()
        await server.register(plane.kv)
        transfer = RemoteTransferBackend(plane.kv)
        prefill_engine = make_engine()
        if mm_transfer == "embeds":
            # the prefill side must never need its vision tower
            def boom(*a, **k):
                raise AssertionError("prefill-side vision tower ran in "
                                     "embeds transfer mode")
            prefill_engine.encode_image = boom
        prefill = PrefillWorker(
            NativeEngineWorker(prefill_engine), queue, transfer,
            plane.messaging)
        await decode.start()
        await prefill.start()
        try:
            pre = PreprocessedRequest(
                request_id="mm1", token_ids=prompt,
                stop=StopConditions(max_tokens=6, ignore_eos=True),
                mm_parts=[ImagePart(offset=4, shape=list(px.shape),
                                    data=px.tobytes())])
            toks = []
            async for frame in decode.generate(
                    pre.model_dump(exclude_none=True), Context("mm1")):
                toks.extend(frame.get("token_ids", ()))
            return toks, decode.remote_prefills
        finally:
            await prefill.stop()
            await decode.stop()
            await transfer.close()
            await server.stop()

    toks, n_remote = asyncio.run(main())
    assert n_remote == 1, "request must take the remote prefill path"
    assert toks == expect


def test_image_span_straddles_prefill_chunks():
    """An image span split across prefill chunks must produce the same
    tokens as a single-chunk prefill (span slicing per chunk window).
    Span occupies prompt [14, 18), straddling the 16-token chunk boundary
    of the chunked engine."""
    emb = make_engine().encode_image(image(3))
    prompt = list(range(30, 44)) + [0] * N_PATCH + list(range(50, 74))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)

    def run(eng, rid):
        eng.add_request(EngineRequest(rid, prompt, params,
                                      mm_spans=[(14, emb)]))
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.token is not None:
                    out.append(ev.token)
        return out

    whole = make_engine(max_prefill_chunk=64, prefill_buckets=(8, 16, 32, 64))
    got_whole = run(whole, "w")
    # sanity: mm embeds must actually influence the output
    expect_raw = make_engine(
        max_prefill_chunk=64, prefill_buckets=(8, 16, 32, 64)).generate(
            prompt, params, "raw")
    assert got_whole != expect_raw

    chunked = make_engine(max_prefill_chunk=16, prefill_buckets=(8, 16))
    got_chunked = run(chunked, "c")
    assert got_chunked == got_whole


# -- pp composition ------------------------------------------------------------

@pytest.mark.parametrize("pp,tp", [(2, 1), (2, 2)])
def test_vision_pp_mesh_exact(pp, tp):
    """Multimodal prefill composes with pp meshes: pp_param_shardings now
    carries the vision subtree and _pp_body mixes the projected patch
    embeds into stage 0's embedding lookup (the same embeds_mask semantics
    as llama.forward). Tokens must match the single-mesh engine exactly.
    Previously rejected at engine init (ROADMAP-1b)."""
    import jax

    from dynamo_tpu.parallel.mesh import make_mesh

    img = image(7)
    oracle = make_engine()
    emb = oracle.encode_image(img)

    def gen(eng, rid, e):
        req = mm_request(rid, e)
        eng.add_request(req)
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.token is not None:
                    out.append(ev.token)
        return out

    expect = gen(oracle, "o", emb)
    # sanity: the image must actually influence the stream (otherwise a
    # pp path that silently dropped the embeds would pass)
    assert expect != oracle.generate(
        [5, 6, 7, 8] + [0] * N_PATCH + [9, 10, 11, 12],
        SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True),
        "raw")

    mesh = make_mesh(pp=pp, tp=tp, devices=jax.devices()[:pp * tp])
    eng = NativeEngine(CFG, EngineConfig(
        page_size=8, num_pages=64, max_slots=2, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=256), mesh=mesh, seed=0)
    emb_pp = eng.encode_image(img)
    np.testing.assert_allclose(np.asarray(emb_pp), np.asarray(emb),
                               rtol=1e-5, atol=1e-5)
    assert gen(eng, "p", emb_pp) == expect
