"""Failpoint registry (runtime/faults.py): determinism units + the
per-site wiring smoke.

Two layers of guarantees:

- **determinism**: a FaultSchedule is a pure function of (seed, specs,
  hit index) — the same seed replays the same faults in the same order,
  survives serialization (`to_dict`/`from_dict`, the chaos_replay
  artifact format) and `reset()`. This is what makes every chaos
  scenario a replayable artifact instead of a flake.
- **wiring**: one tier-1-safe smoke per failpoint site class, arming the
  REAL call site (memory plane ops, prefill queue, offload tiers, the
  transfer staging hop, lease keep-alive) and asserting the fault
  lands. This is the bit-rot guard: a refactor that silently unthreads
  a site from the registry fails here, not in a 3-minute chaos run.
"""
import asyncio

import numpy as np
import pytest

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.faults import (
    FaultInjected, FaultRegistry, FaultSchedule, FaultSpec, REGISTRY, SITES,
)
from dynamo_tpu.runtime.integrity import STATS as INTEGRITY


@pytest.fixture(autouse=True)
def clean_registry():
    """Every test starts and ends disarmed with zeroed counters — a
    leaked armed site would contaminate every later test in the
    process (the registry is process-global by design)."""
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()
    yield
    REGISTRY.disarm()
    REGISTRY.reset_counters()
    INTEGRITY.reset()


# -- schedule determinism ------------------------------------------------------

def drain(sched: FaultSchedule, n: int = 64):
    return [sched.decide() for _ in range(n)]


def test_same_seed_same_decisions():
    specs = [FaultSpec("drop", p=0.3), FaultSpec("delay", p=0.5,
                                                 delay_s=0.01)]
    a = drain(FaultSchedule(7, specs))
    b = drain(FaultSchedule(7, specs))
    assert a == b
    assert any(o.fired for o in a)      # the seed actually fires things


def test_different_seed_different_decisions():
    specs = [FaultSpec("drop", p=0.5)]
    assert drain(FaultSchedule(1, specs)) != drain(FaultSchedule(2, specs))


def test_serialization_round_trip_replays():
    sched = FaultSchedule(42, [FaultSpec("corrupt", p=0.4, n=3, nbytes=2),
                               FaultSpec("drop", p=0.1)])
    clone = FaultSchedule.from_dict(sched.to_dict())
    assert drain(sched) == drain(clone)


def test_reset_rewinds_to_hit_zero():
    sched = FaultSchedule(13, [FaultSpec("drop", p=0.5)])
    first = drain(sched)
    sched.reset()
    assert drain(sched) == first


def test_fail_n_fails_exactly_first_n():
    sched = FaultSchedule(0, [FaultSpec("fail_n", n=3)])
    outs = drain(sched, 10)
    assert [o.drop for o in outs] == [True] * 3 + [False] * 7


def test_skip_pins_fault_to_a_hit_index():
    """skip=k leaves the rule dormant for the first k hits: a fail_n
    with skip=2, n=1 cuts EXACTLY the third hit — how the transfer
    resume matrix seeds a link cut at a chosen chunk index."""
    sched = FaultSchedule(0, [FaultSpec("fail_n", n=1, skip=2)])
    outs = drain(sched, 8)
    assert [o.drop for o in outs] == [False, False, True] + [False] * 5
    # skip still consumes the per-hit draw: a trailing spec's decisions
    # are unchanged by the leading spec's dormancy
    paired = FaultSchedule(3, [FaultSpec("fail_n", n=1, skip=2),
                               FaultSpec("drop", p=0.5)])
    inert = FaultSchedule(3, [FaultSpec("drop", p=0.0),
                              FaultSpec("drop", p=0.5)])
    a, b = drain(paired, 16), drain(inert, 16)
    assert [x.drop for x in a[3:]] == [x.drop for x in b[3:]]


def test_delay_min_floors_the_seeded_draw():
    """delay_min_s == delay_s is a DETERMINISTIC stall of exactly that
    length (how a chaos plan wedges a sender so a worker kill lands
    mid-transfer); a plain delay stays in [0, delay_s]."""
    sched = FaultSchedule(1, [FaultSpec("delay", p=1.0, delay_s=2.5,
                                        delay_min_s=2.5)])
    assert [o.delay_s for o in drain(sched, 4)] == [2.5] * 4
    lo = FaultSchedule(1, [FaultSpec("delay", p=1.0, delay_s=2.0,
                                     delay_min_s=1.0)])
    assert all(1.0 <= o.delay_s <= 2.0 for o in drain(lo, 16))


def test_bounded_corrupt_fires_at_most_n_times():
    sched = FaultSchedule(5, [FaultSpec("corrupt", p=1.0, n=2)])
    outs = drain(sched, 20)
    assert sum(o.corrupt for o in outs) == 2
    assert all(o.corrupt for o in outs[:2])   # p=1: the first two hits


def test_outcomes_do_not_shift_the_stream():
    """A spec exhausting its budget must not change LATER specs'
    decisions: hit k's outcome is a function of k alone (the property
    that makes a recorded schedule replayable against code that hits
    the site a different number of times before the interesting
    window)."""
    with_budget = FaultSchedule(9, [FaultSpec("fail_n", n=2),
                                    FaultSpec("drop", p=0.5)])
    # same seed, first spec replaced by one that never fires but still
    # consumes its one draw per hit
    inert_first = FaultSchedule(9, [FaultSpec("drop", p=0.0),
                                    FaultSpec("drop", p=0.5)])
    a = drain(with_budget, 32)
    b = drain(inert_first, 32)
    # past the fail_n budget, the second spec's pattern is identical
    assert [x.drop for x in a[2:]] == [x.drop for x in b[2:]]


def test_slow_kind_persistent_factor_and_max_combining():
    """`slow` is PERSISTENT degradation: every firing hit reports the
    same multiplicative factor (not a one-shot delay), and two armed
    slow specs combine by max — the worst rule wins, factors never
    stack multiplicatively."""
    sched = FaultSchedule(3, [FaultSpec("slow", p=1.0, factor=8.0)])
    assert [o.slow_factor for o in drain(sched, 6)] == [8.0] * 6
    both = FaultSchedule(3, [FaultSpec("slow", p=1.0, factor=8.0),
                             FaultSpec("slow", p=1.0, factor=3.0)])
    assert [o.slow_factor for o in drain(both, 6)] == [8.0] * 6


def test_slow_kind_seeded_intermittence_replays():
    """p < 1 models a flapping gray failure (NIC that degrades in
    bursts): which hits degrade is a pure function of the seed, and a
    non-firing hit reports the neutral factor 1.0."""
    specs = [FaultSpec("slow", p=0.4, factor=5.0)]
    a = [o.slow_factor for o in drain(FaultSchedule(11, specs), 40)]
    b = [o.slow_factor for o in drain(FaultSchedule(11, specs), 40)]
    assert a == b
    assert set(a) == {1.0, 5.0}


def test_slow_spec_consumes_one_draw_per_hit():
    """A `slow` spec ahead of another spec consumes exactly one rng
    draw per hit, firing or not — replacing it with an inert spec
    leaves the later spec's decision stream untouched (the same
    stream-stability property test_outcomes_do_not_shift_the_stream
    pins for fail_n)."""
    with_slow = FaultSchedule(9, [FaultSpec("slow", p=0.4, factor=4.0),
                                  FaultSpec("drop", p=0.5)])
    inert = FaultSchedule(9, [FaultSpec("drop", p=0.0),
                              FaultSpec("drop", p=0.5)])
    assert [x.drop for x in drain(with_slow, 32)] == \
        [x.drop for x in drain(inert, 32)]


def test_registry_slow_factor_counts_hits_and_disarmed_is_neutral():
    """REGISTRY.slow_factor(site) is a site hook like fire/decide: it
    advances the decision stream (counts a hit) while armed, and is the
    neutral 1.0 with zero bookkeeping when disarmed."""
    assert REGISTRY.slow_factor("transport.send") == 1.0
    assert REGISTRY.snapshot()["hits"] == {}
    REGISTRY.arm("transport.send", FaultSchedule(
        5, [FaultSpec("slow", p=1.0, factor=10.0)]))
    assert REGISTRY.slow_factor("transport.send") == 10.0
    assert REGISTRY.slow_factor("transport.send") == 10.0
    assert REGISTRY.snapshot()["hits"]["transport.send"] == 2
    REGISTRY.disarm()
    assert REGISTRY.slow_factor("transport.send") == 1.0


def test_unknown_kind_and_site_rejected():
    with pytest.raises(ValueError):
        FaultSpec("explode")
    with pytest.raises(ValueError):
        FaultRegistry().arm("transport.teleport",
                            FaultSchedule(0, [FaultSpec("drop")]))


def test_disarmed_registry_is_inert():
    reg = FaultRegistry()
    assert not reg.enabled
    assert asyncio.run(reg.fire("transport.send")) == faults.Outcome()
    assert reg.fire_sync("queue.dequeue") == faults.Outcome()
    payload = b"untouched"
    assert reg.corrupt_bytes("remote_transfer.fetch_page", payload) \
        is payload
    reg.arm("transport.send", FaultSchedule(0, [FaultSpec("drop")]))
    assert reg.enabled
    reg.disarm()
    assert not reg.enabled


def test_registry_plan_round_trip():
    reg = FaultRegistry()
    reg.arm("transport.send", FaultSchedule(3, [FaultSpec("drop", p=0.5)]))
    reg.arm("queue.dequeue", FaultSchedule(4, [FaultSpec("delay",
                                                         delay_s=0.01)]))
    clone = FaultRegistry()
    clone.arm_from_dict(reg.to_dict())
    assert clone.to_dict() == reg.to_dict()
    assert set(clone.to_dict()) == {"transport.send", "queue.dequeue"}


def test_counters_distinguish_hits_from_injections():
    reg = FaultRegistry()
    reg.arm("transport.send", FaultSchedule(0, [FaultSpec("fail_n", n=1)]))
    with pytest.raises(FaultInjected):
        reg.fire_sync("transport.send")
    reg.fire_sync("transport.send")   # budget spent: passes
    snap = reg.snapshot()
    assert snap["hits"]["transport.send"] == 2
    assert snap["injected"]["transport.send"] == 1


# -- per-site wiring smoke -----------------------------------------------------
# One armed failpoint per site class, against the REAL call site. Cheap
# enough for tier-1; failing here means a refactor unthreaded the site.

def arm(site, *specs, seed=0):
    REGISTRY.arm(site, FaultSchedule(seed, list(specs)))


def test_site_transport_send_drop_reaches_kv_caller():
    from dynamo_tpu.runtime.transports.memory import MemoryKVStore

    async def main():
        kv = MemoryKVStore()
        arm("transport.send", FaultSpec("fail_n", n=1))
        with pytest.raises(ConnectionError):   # FaultInjected IS one
            await kv.put("k", b"v")
        await kv.put("k", b"v")                # budget spent: succeeds
        assert await kv.get("k") == b"v"

    asyncio.run(main())
    assert REGISTRY.snapshot()["injected"]["transport.send"] == 1


def test_site_transport_recv_drops_and_duplicates_deliveries():
    from dynamo_tpu.runtime.transports.memory import MemoryMessaging

    async def main():
        msg = MemoryMessaging()
        sub = await msg.subscribe("ev.>")
        agen = sub.__aiter__()
        # fail_n drops the first delivery; the duplicate spec fires on
        # the first two hits, but hit 1's drop wins (a lost frame can't
        # also arrive twice), so only hit 2 actually doubles
        arm("transport.recv", FaultSpec("fail_n", n=1),
            FaultSpec("duplicate", p=1.0, n=2))
        await msg.publish("ev.a", b"lost")        # dropped for this sub
        await msg.publish("ev.a", b"doubled")     # duplicated
        await msg.publish("ev.a", b"normal")
        got = [await asyncio.wait_for(agen.__anext__(), 5)
               for _ in range(3)]
        assert [p for _, p in got] == [b"doubled", b"doubled", b"normal"]

    asyncio.run(main())


def test_site_queue_dequeue_fault_loses_no_items():
    from dynamo_tpu.disagg.protocols import RemotePrefillRequest
    from dynamo_tpu.disagg.queue import PrefillQueue
    from dynamo_tpu.runtime.transports.memory import MemoryMessaging

    async def main():
        q = PrefillQueue(MemoryMessaging(), "ns", "tiny")
        await q.enqueue(RemotePrefillRequest(
            engine_id="e", request_id="r1", token_ids=[1, 2, 3],
            page_ids=[0]))
        arm("queue.dequeue", FaultSpec("fail_n", n=1))
        with pytest.raises(FaultInjected):
            await q.dequeue(timeout=0.1)
        # the failpoint fires BEFORE the pop: the item is still queued
        got = await q.dequeue(timeout=1.0)
        assert got is not None and got.request_id == "r1"

    asyncio.run(main())


def test_site_offload_write_tier_corruption_is_quarantined_on_read():
    from dynamo_tpu.engine.offload import HostKvPool
    arm("offload.write_tier", FaultSpec("corrupt", p=1.0, n=1))
    pool = HostKvPool(capacity=4, page_shape=(2, 8), dtype=np.float32)
    page = np.arange(16, dtype=np.float32).reshape(2, 8)
    pool.put(0xAB, page, page + 1)    # write-tier rot flips stored bytes
    assert pool.get(0xAB) is None     # verify-on-fetch: quarantined
    assert INTEGRITY.quarantined == 1 and INTEGRITY.mismatches == 1
    assert pool.get(0xAB) is None     # gone, not resurrectable


def test_site_offload_read_tier_corruption_is_quarantined():
    from dynamo_tpu.engine.offload import HostKvPool
    pool = HostKvPool(capacity=4, page_shape=(2, 8), dtype=np.float32)
    page = np.arange(16, dtype=np.float32).reshape(2, 8)
    pool.put(0xCD, page, page + 1)    # clean write
    arm("offload.read_tier", FaultSpec("corrupt", p=1.0, n=1))
    assert pool.get(0xCD) is None     # rot surfaced at read: quarantined
    assert INTEGRITY.quarantined == 1


def test_site_remote_transfer_corruption_refetches_then_succeeds():
    import jax.numpy as jnp

    from dynamo_tpu.disagg.transfer import LocalTransferBackend
    arm("remote_transfer.fetch_page", FaultSpec("corrupt", p=1.0, n=1))
    k = jnp.arange(2 * 2 * 2 * 4, dtype=jnp.float32).reshape(2, 2, 2, 4)
    v = k + 100.0
    k_np, v_np, ks_np, vs_np = asyncio.run(
        LocalTransferBackend._verified_stage("r1", [0, 1], k, v))
    # the single bounded corruption was absorbed by one re-fetch and the
    # verified bytes match the authoritative device copy (unquantized
    # pages carry no scale stacks)
    assert ks_np is None and vs_np is None
    np.testing.assert_array_equal(k_np, np.asarray(k))
    np.testing.assert_array_equal(v_np, np.asarray(v))
    assert INTEGRITY.refetches == 1 and INTEGRITY.mismatches >= 1
    assert INTEGRITY.quarantined == 0


def test_site_transfer_link_cut_reaches_sender_gate():
    """Wiring smoke: the transfer.link site fires on the sender's
    per-chunk gate as a ConnectionError (FaultInjected), which is what
    routes it into the resume path rather than a crash."""
    from dynamo_tpu.disagg.remote_transfer import RemoteTransferBackend
    from dynamo_tpu.runtime.transports.memory import MemoryPlane

    backend = RemoteTransferBackend(MemoryPlane().kv)
    arm("transfer.link", FaultSpec("fail_n", n=1, skip=1))

    async def main():
        await backend._chunk_gate(0)          # hit 1: dormant (skip)
        with pytest.raises(ConnectionError):  # hit 2: the seeded cut
            await backend._chunk_gate(1)
        await backend._chunk_gate(2)          # budget spent: link healthy

    asyncio.run(main())
    assert REGISTRY.snapshot()["injected"]["transfer.link"] == 1


def test_site_discovery_heartbeat_drop_skips_lease_refresh():
    from dynamo_tpu.runtime.transports.memory import MemoryKVStore

    async def main():
        kv = MemoryKVStore()
        lease = await kv.grant_lease(ttl=30.0)
        before = kv._lease_deadline[lease.id]
        arm("discovery.heartbeat", FaultSpec("fail_n", n=1))
        lease.keep_alive()            # heartbeat lost: no refresh
        assert kv._lease_deadline[lease.id] == before
        lease.keep_alive()            # budget spent: refresh lands
        assert kv._lease_deadline[lease.id] > before
        await lease.revoke()

    asyncio.run(main())
    snap = REGISTRY.snapshot()
    assert snap["injected"]["discovery.heartbeat"] == 1


def test_site_discovery_store_window_fails_then_recovers():
    from dynamo_tpu.runtime.transports.memory import MemoryKVStore

    async def main():
        kv = MemoryKVStore()
        await kv.put("k", b"v")
        arm("discovery.store", FaultSpec("fail_n", n=1))
        with pytest.raises(ConnectionError):   # unavailable window
            await kv.get("k")
        assert await kv.get("k") == b"v"       # window over

    asyncio.run(main())
    assert REGISTRY.snapshot()["injected"]["discovery.store"] == 1


def test_site_lease_expiry_force_expires_lease():
    from dynamo_tpu.runtime.transports.memory import MemoryKVStore

    async def main():
        kv = MemoryKVStore()
        lease = await kv.grant_lease(ttl=0.9)
        await kv.put("k", b"v", lease.id)
        # the first watchdog tick (~ttl/3) force-expires, well before
        # the 0.9s natural deadline
        arm("lease.expiry", FaultSpec("drop", p=1.0, n=1))
        await asyncio.wait_for(lease.lost.wait(), 10)
        assert await kv.get("k") is None       # leased key swept

    asyncio.run(main())
    assert REGISTRY.snapshot()["injected"]["lease.expiry"] >= 1


def test_site_event_plane_delay_reorders_delivery():
    from dynamo_tpu.runtime.transports.memory import MemoryMessaging

    async def main():
        msg = MemoryMessaging()
        sub = await msg.subscribe("ev.>")
        # hit 1 delayed via call_later; hit 2 (budget spent) immediate —
        # the delayed event arrives LATE and OUT OF ORDER, the lag model
        # the router's degraded mode is built against
        arm("event.plane", FaultSpec("delay", p=1.0, n=1, delay_s=0.2))
        await msg.publish("ev.a", b"delayed")
        await msg.publish("ev.a", b"prompt")
        got = [await asyncio.wait_for(sub.__anext__(), 5)
               for _ in range(2)]
        assert [p for _, p in got] == [b"prompt", b"delayed"]

    asyncio.run(main())


def test_site_watch_stream_drop_raises_into_consumer():
    from dynamo_tpu.runtime.transports.memory import MemoryKVStore

    async def main():
        kv = MemoryKVStore()
        snapshot, stream = await kv.watch_prefix("p/")
        arm("watch.stream", FaultSpec("fail_n", n=1))
        await kv.put("p/a", b"1")
        with pytest.raises(FaultInjected):     # the disconnect model
            await asyncio.wait_for(stream.__anext__(), 5)
        # a RE-ESTABLISHED stream works; the event lost with the old one
        # is recovered by the snapshot (what Client._watch_loop does)
        snapshot2, stream2 = await kv.watch_prefix("p/")
        assert [e.key for e in snapshot2] == ["p/a"]
        await kv.put("p/b", b"2")
        ev = await asyncio.wait_for(stream2.__anext__(), 5)
        assert ev.key == "p/b"
        await stream.aclose()
        await stream2.aclose()

    asyncio.run(main())


def test_every_catalogued_site_is_armable():
    for site in SITES:
        arm(site, FaultSpec("drop", p=0.0))
        assert REGISTRY.armed(site)


# -- chaos_replay tool ---------------------------------------------------------

def _load_chaos_replay():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "chaos_replay.py")
    spec = importlib.util.spec_from_file_location("chaos_replay", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_replay_scenario_names_in_sync():
    """The replay tool's static menu (kept import-light for --list) must
    track the harness's actual scenario registry."""
    import test_chaos
    mod = _load_chaos_replay()
    assert set(mod.SCENARIO_NAMES) == set(test_chaos.SCENARIOS)


def test_chaos_replay_cli_list_is_cheap_and_clean():
    import os
    import subprocess
    import sys
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "chaos_replay.py")
    proc = subprocess.run([sys.executable, path, "--list"],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    names = proc.stdout.split()
    assert "rolling_restart" in names and len(names) >= 3
