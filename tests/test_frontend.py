"""HTTP frontend tests: OpenAI routes, SSE, metrics, discovery, e2e serving.

Mirrors the reference's http-service tests (SURVEY.md §4.2: real server +
CounterEngine/AlwaysFailEngine fakes, Prometheus counters/inflight asserted,
SSE behavior) plus the full distributed path: echo worker over the in-memory
control plane, model registration, KV-routed native-engine serving.
"""
import asyncio
import json

import pytest

from dynamo_tpu.frontend.discovery import (
    ModelWatcher, list_registered_models, register_model, unregister_model,
)
from dynamo_tpu.frontend.service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.pipeline import LocalPipeline
from dynamo_tpu.llm.worker import EchoTokenEngine, serve_llm_worker
from dynamo_tpu.observability.metrics import MetricsRegistry
from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk, ChatCompletionRequest, ChatStreamChoice,
    new_response_id, now,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane

from tests.http_client import request, sse_events


def run(coro):
    return asyncio.run(coro)


class CounterEngine:
    """Streams n numbered chunks (reference CounterEngine fake)."""

    def __init__(self, n=3, delay=0.0):
        self.n = n
        self.delay = delay
        self.contexts = []

    async def generate_chat(self, request, context):
        self.contexts.append(context)
        gen_id, created = new_response_id("chatcmpl"), now()
        for i in range(self.n):
            if context.is_stopped:
                return
            if self.delay:
                await asyncio.sleep(self.delay)
            yield ChatCompletionChunk(
                id=gen_id, created=created, model=request.model,
                choices=[ChatStreamChoice(
                    index=0, delta={"role": "assistant", "content": f"c{i} "})])
        yield ChatCompletionChunk(
            id=gen_id, created=created, model=request.model,
            choices=[ChatStreamChoice(index=0, delta={},
                                      finish_reason="stop")])

    async def generate_completion(self, request, context):
        raise NotImplementedError
        yield


class AlwaysFailEngine:
    async def generate_chat(self, request, context):
        raise RuntimeError("boom")
        yield

    generate_completion = generate_chat


CHAT_BODY = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}


class TestHttpService:
    def test_unary_chat_aggregates_and_counts(self):
        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", CounterEngine(3))
            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY)
            assert status == 200
            resp = json.loads(body)
            assert resp["choices"][0]["message"]["content"] == "c0 c1 c2 "
            assert resp["choices"][0]["finish_reason"] == "stop"
            assert svc._requests.get("m", "chat", "unary", "success") == 1
            assert svc._inflight.get("m") == 0
            assert svc._duration.count("m") == 1
            await svc.stop()

        run(main())

    def test_tools_request_parses_tool_call_response(self):
        """A tools-carrying chat request whose generated text is a tool
        invocation comes back as OpenAI tool_calls with finish_reason
        'tool_calls' (reference: preprocessor/tools/response.rs)."""
        class ToolEngine(CounterEngine):
            async def generate_chat(self, request, context):
                gen_id, created = new_response_id("chatcmpl"), now()
                text = '{"name": "get_weather", "arguments": {"c": "Oslo"}}'
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(
                        index=0,
                        delta={"role": "assistant", "content": text})])
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(index=0, delta={},
                                              finish_reason="stop")])

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", ToolEngine())
            body = {**CHAT_BODY,
                    "tools": [{"type": "function",
                               "function": {"name": "get_weather"}}]}
            status, raw = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions", body)
            assert status == 200
            choice = json.loads(raw)["choices"][0]
            assert choice["finish_reason"] == "tool_calls"
            tc = choice["message"]["tool_calls"][0]
            assert tc["function"]["name"] == "get_weather"
            assert json.loads(tc["function"]["arguments"]) == {"c": "Oslo"}
            assert "content" not in choice["message"]

            # WITHOUT tools, the same text stays plain content
            status2, raw2 = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY)
            choice2 = json.loads(raw2)["choices"][0]
            assert choice2["finish_reason"] == "stop"
            assert choice2["message"]["content"].startswith('{"name"')
            await svc.stop()

        run(main())

    def test_tools_streaming_n2_prose_choice_streams_live(self):
        """VERDICT r4 weak #5: in an n>1 tools-carrying stream, a choice
        whose head disqualifies as a tool call streams LIVE even while a
        sibling choice is still a tool-call candidate. The fake engine
        refuses to emit the tool-call choice until the client has already
        RECEIVED prose deltas — under whole-stream buffering this
        deadlocks (and times out); per-choice candidacy passes."""
        class MixedEngine(CounterEngine):
            def __init__(self):
                super().__init__()
                self.release = asyncio.Event()

            async def generate_chat(self, request, context):
                gen_id, created = new_response_id("chatcmpl"), now()

                def chunk(idx, delta, fin=None):
                    return ChatCompletionChunk(
                        id=gen_id, created=created, model=request.model,
                        choices=[ChatStreamChoice(index=idx, delta=delta,
                                                  finish_reason=fin)])

                yield chunk(1, {"role": "assistant", "content": "Sure, "})
                yield chunk(1, {"content": "here is prose"})
                # blocks until the CLIENT saw the prose — proves release
                # happened before this choice's stream finished
                await asyncio.wait_for(self.release.wait(), 15)
                yield chunk(0, {"role": "assistant",
                                "content": '{"name": "f", '})
                yield chunk(0, {"content": '"arguments": {"x": 1}}'})
                yield chunk(0, {}, "stop")
                yield chunk(1, {}, "stop")

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            eng = MixedEngine()
            svc.models.add("m", eng)
            body = {**CHAT_BODY, "stream": True, "n": 2,
                    "tools": [{"type": "function",
                               "function": {"name": "f"}}]}
            datas = []
            async for _ev, d in sse_events(
                    "127.0.0.1", svc.port, "/v1/chat/completions", body):
                if d == "[DONE]":
                    continue
                c = json.loads(d)
                datas.append(c)
                for ch in c["choices"]:
                    if ch["index"] == 1 and ch["delta"].get("content"):
                        eng.release.set()
            prose = "".join(ch["delta"].get("content") or ""
                            for c in datas for ch in c["choices"]
                            if ch["index"] == 1)
            assert prose == "Sure, here is prose"
            tool = next(ch for c in datas for ch in c["choices"]
                        if ch["index"] == 0 and
                        ch["delta"].get("tool_calls"))
            assert tool["delta"]["tool_calls"][0]["function"]["name"] == "f"
            fins = {ch["index"]: ch["finish_reason"]
                    for c in datas for ch in c["choices"]
                    if ch.get("finish_reason")}
            assert fins[0] == "tool_calls" and fins[1] == "stop"
            await svc.stop()

        run(main())

    def test_tools_streaming_emits_tool_call_deltas(self):
        """stream=true with tools must behave like unary: the buffered
        stream resolves into delta.tool_calls + finish 'tool_calls', and
        plain prose replays as normal content deltas."""
        class ToolEngine(CounterEngine):
            def __init__(self, text):
                super().__init__()
                self.text = text

            async def generate_chat(self, request, context):
                gen_id, created = new_response_id("chatcmpl"), now()
                for piece in (self.text[:8], self.text[8:]):
                    yield ChatCompletionChunk(
                        id=gen_id, created=created, model=request.model,
                        choices=[ChatStreamChoice(
                            index=0,
                            delta={"role": "assistant", "content": piece})])
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(index=0, delta={},
                                              finish_reason="stop")])

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add(
                "m", ToolEngine('{"name": "f", "arguments": {"x": 1}}'))
            svc.models.add("p", ToolEngine("just some prose here"))
            body = {**CHAT_BODY, "stream": True,
                    "tools": [{"type": "function",
                               "function": {"name": "f"}}]}
            datas = [json.loads(d) async for ev, d in sse_events(
                "127.0.0.1", svc.port, "/v1/chat/completions", body)
                if d != "[DONE]"]
            deltas = [c["choices"][0] for c in datas if c["choices"]]
            tool_delta = next(d for d in deltas
                              if d["delta"].get("tool_calls"))
            assert tool_delta["delta"]["tool_calls"][0]["function"][
                "name"] == "f"
            assert deltas[-1]["finish_reason"] == "tool_calls"
            assert not any(d["delta"].get("content") for d in deltas)

            # prose through the same buffered path replays as content
            body2 = {**body, "model": "p"}
            datas2 = [json.loads(d) async for ev, d in sse_events(
                "127.0.0.1", svc.port, "/v1/chat/completions", body2)
                if d != "[DONE]"]
            text = "".join(
                c["choices"][0]["delta"].get("content") or ""
                for c in datas2 if c["choices"])
            assert text == "just some prose here"
            await svc.stop()

        run(main())

    def test_tools_streaming_prose_passes_through_live(self):
        """VERDICT r3 weak #5: a tools-carrying stream whose head cannot
        be a tool-call dialect must stream LIVE, not buffer-to-finish.
        The engine refuses to emit its second chunk until the client has
        observed the first prose delta — only real passthrough (flush on
        the non-candidate head) can complete this exchange."""
        gate = asyncio.Event()

        class GatedProseEngine(CounterEngine):
            async def generate_chat(self, request, context):
                gen_id, created = new_response_id("chatcmpl"), now()
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(
                        index=0,
                        delta={"role": "assistant", "content": "Sure — "})])
                await gate.wait()  # held forever under buffer-to-finish
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(
                        index=0, delta={"content": "42."})])
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(index=0, delta={},
                                              finish_reason="stop")])

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", GatedProseEngine())
            body = {**CHAT_BODY, "stream": True,
                    "tools": [{"type": "function",
                               "function": {"name": "f"}}]}
            content_deltas = []
            async for ev, d in sse_events(
                    "127.0.0.1", svc.port, "/v1/chat/completions", body):
                if d == "[DONE]":
                    break
                c = json.loads(d)
                for ch in c["choices"]:
                    if ch["delta"].get("content"):
                        content_deltas.append(ch["delta"]["content"])
                        gate.set()  # first delta arrived mid-generation
            assert content_deltas == ["Sure — ", "42."]
            await svc.stop()

        run(asyncio.wait_for(main(), timeout=30))

    def test_tools_streaming_mid_text_tag_resolves_like_unary(self):
        """A Hermes-style <tool_call> tag AFTER prose (the one dialect the
        unary parser matches anywhere in the text) must still come back as
        delta.tool_calls + finish 'tool_calls' even though the prose head
        already streamed live — the stream-mode tag watch holds from the
        first possible tag start."""
        pieces = ["Let me check. ", "<tool",
                  '_call>{"name": "f", "arguments": {"x": 1}}</tool_call>']

        class MidTagEngine(CounterEngine):
            async def generate_chat(self, request, context):
                gen_id, created = new_response_id("chatcmpl"), now()
                for piece in pieces:
                    yield ChatCompletionChunk(
                        id=gen_id, created=created, model=request.model,
                        choices=[ChatStreamChoice(
                            index=0,
                            delta={"role": "assistant", "content": piece})])
                yield ChatCompletionChunk(
                    id=gen_id, created=created, model=request.model,
                    choices=[ChatStreamChoice(index=0, delta={},
                                              finish_reason="stop")])

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", MidTagEngine())
            body = {**CHAT_BODY, "stream": True,
                    "tools": [{"type": "function",
                               "function": {"name": "f"}}]}
            deltas = []
            async for ev, d in sse_events(
                    "127.0.0.1", svc.port, "/v1/chat/completions", body):
                if d == "[DONE]":
                    break
                c = json.loads(d)
                deltas.extend(c["choices"])
            # the prose head streamed as content
            assert any(ch["delta"].get("content") == "Let me check. "
                       for ch in deltas)
            tool_delta = next(ch for ch in deltas
                              if ch["delta"].get("tool_calls"))
            tc = tool_delta["delta"]["tool_calls"][0]
            assert tc["function"]["name"] == "f"
            assert json.loads(tc["function"]["arguments"]) == {"x": 1}
            assert deltas[-1]["finish_reason"] == "tool_calls"
            # the raw tag text never leaked as content
            assert not any("<tool_call>" in (ch["delta"].get("content")
                                             or "") for ch in deltas)
            await svc.stop()

        run(asyncio.wait_for(main(), timeout=30))

    def test_streaming_sse_with_done(self):
        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", CounterEngine(2))
            events = []
            async for ev, data in sse_events(
                    "127.0.0.1", svc.port, "/v1/chat/completions",
                    {**CHAT_BODY, "stream": True}):
                events.append((ev, data))
            assert events[-1][1] == "[DONE]"
            contents = [json.loads(d)["choices"][0]["delta"].get("content")
                        for _, d in events[:-2]]
            assert contents == ["c0 ", "c1 "]
            assert svc._requests.get("m", "chat", "stream", "success") == 1
            await svc.stop()

        run(main())

    def test_client_disconnect_stops_generation(self):
        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            eng = CounterEngine(1000, delay=0.01)
            svc.models.add("m", eng)
            gen = sse_events("127.0.0.1", svc.port, "/v1/chat/completions",
                             {**CHAT_BODY, "stream": True}, max_events=3)
            got = [d async for _, d in gen]
            assert len(got) == 3  # connection dropped after 3 events
            for _ in range(100):
                if eng.contexts and eng.contexts[0].is_stopped:
                    break
                await asyncio.sleep(0.05)
            assert eng.contexts[0].is_stopped
            assert svc._inflight.get("m") == 0
            await svc.stop()

        run(main())

    def test_errors_and_statuses(self):
        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m", AlwaysFailEngine())
            # unknown model -> 404
            status, _ = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                {**CHAT_BODY, "model": "nope"})
            assert status == 404
            # invalid body -> 422
            status, _ = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                {"model": "m"})
            assert status == 422
            # wrong method -> 405
            status, _ = await request(
                "127.0.0.1", svc.port, "GET", "/v1/chat/completions")
            assert status == 405
            # unknown path -> 404
            status, _ = await request("127.0.0.1", svc.port, "GET", "/nope")
            assert status == 404
            # engine failure -> 500 + error counter
            status, _ = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY)
            assert status == 500
            assert svc._requests.get("m", "chat", "unary", "error") == 1
            await svc.stop()

        run(main())

    def test_load_shedding_429_with_retry_after(self):
        """Admission control: past max_inflight + max_queued the service
        sheds with 429 + Retry-After, and every ACCEPTED request still
        completes once capacity frees up."""
        from dynamo_tpu.frontend.reliability import AdmissionControl

        class GatedEngine(CounterEngine):
            def __init__(self):
                super().__init__(n=1)
                self.gate = asyncio.Event()
                self.started = 0

            async def generate_chat(self, request, context):
                self.started += 1
                await self.gate.wait()
                async for c in super().generate_chat(request, context):
                    yield c

        async def main():
            eng = GatedEngine()
            svc = await HttpService(
                "127.0.0.1", 0,
                admission=AdmissionControl(max_inflight=1, max_queued=1,
                                           queue_timeout_s=10.0,
                                           retry_after_s=3)).start()
            svc.models.add("m", eng)

            t1 = asyncio.create_task(request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY))
            for _ in range(200):   # t1 admitted and inside the engine
                if eng.started:
                    break
                await asyncio.sleep(0.01)
            t2 = asyncio.create_task(request(     # queued behind t1
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY))
            await asyncio.sleep(0.05)
            # queue full: this one is shed immediately
            status, body, headers = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                CHAT_BODY, return_headers=True)
            assert status == 429, body
            assert headers.get("retry-after") == "3"
            assert json.loads(body)["error"]["code"] == 429
            assert svc.reliability.shed_requests.get() == 1
            assert svc._requests.get("m", "chat", "unary", "shed") == 1

            eng.gate.set()   # capacity frees: both accepted requests finish
            (s1, b1), (s2, b2) = await asyncio.wait_for(
                asyncio.gather(t1, t2), 15)
            assert s1 == 200 and s2 == 200
            for b in (b1, b2):
                assert json.loads(b)["choices"][0]["message"]["content"] \
                    == "c0 "
            assert svc.admission.active == 0
            # shed requests never touched inflight accounting
            assert svc._inflight.get("m") == 0
            await svc.stop()

        run(asyncio.wait_for(main(), 30))

    def test_models_and_metrics_routes(self):
        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("m1", CounterEngine(), "chat")
            svc.models.add("m2", CounterEngine(), "completion")
            status, body = await request("127.0.0.1", svc.port, "GET",
                                         "/v1/models")
            assert status == 200
            assert [m["id"] for m in json.loads(body)["data"]] == ["m1", "m2"]
            await request("127.0.0.1", svc.port, "POST",
                          "/v1/chat/completions", {**CHAT_BODY, "model": "m1"})
            status, body = await request("127.0.0.1", svc.port, "GET",
                                         "/metrics")
            text = body.decode()
            assert status == 200
            assert ('llm_http_service_requests_total{model="m1",'
                    'endpoint="chat",request_type="unary",status="success"} 1'
                    in text)
            assert "# TYPE llm_http_service_request_duration_seconds histogram" \
                in text
            await svc.stop()

        run(main())

    def test_metrics_surface_fault_integrity_drain_counters(self):
        """The robustness counters — failpoint hits/injections, KV
        integrity, graceful drain — are folded into /metrics at render
        time from their process-global stats objects."""
        from dynamo_tpu.runtime import faults
        from dynamo_tpu.runtime.component import DRAIN_STATS
        from dynamo_tpu.runtime.faults import (
            FaultInjected, FaultSchedule, FaultSpec,
        )
        from dynamo_tpu.runtime.integrity import STATS as integrity

        async def main():
            svc = await HttpService("127.0.0.1", 0).start()
            faults.REGISTRY.arm("queue.dequeue", FaultSchedule(
                0, [FaultSpec("fail_n", n=1)]))
            with pytest.raises(FaultInjected):
                faults.REGISTRY.fire_sync("queue.dequeue")
            integrity.pages_hashed += 3
            integrity.quarantined += 1
            DRAIN_STATS.drains_started += 1
            DRAIN_STATS.drains_completed += 1
            try:
                status, body = await request("127.0.0.1", svc.port, "GET",
                                             "/metrics")
                text = body.decode()
                assert status == 200
                hits = faults.REGISTRY.site_hits["queue.dequeue"]
                inj = faults.REGISTRY.injected["queue.dequeue"]
                assert f'llm_fault_site_hits{{site="queue.dequeue"}} ' \
                    f'{hits}' in text
                assert f'llm_fault_injections{{site="queue.dequeue"}} ' \
                    f'{inj}' in text
                assert f"llm_kv_integrity_pages_hashed " \
                    f"{integrity.pages_hashed}" in text
                assert f"llm_kv_integrity_quarantined " \
                    f"{integrity.quarantined}" in text
                assert f"llm_drain_drains_completed " \
                    f"{DRAIN_STATS.drains_completed}" in text
                # control-plane gauges ride the same render-time fold
                from dynamo_tpu.runtime.cpstats import CP_STATS
                assert "llm_cp_router_degraded " \
                    f"{int(CP_STATS.router_degraded)}" in text
                assert "llm_cp_watch_resyncs " \
                    f"{int(CP_STATS.watch_resyncs)}" in text
            finally:
                faults.REGISTRY.disarm()
                faults.REGISTRY.reset_counters()
                integrity.reset()
                await svc.stop()

        run(main())


def byte_card(name="echo-model", **kw):
    return ModelDeploymentCard(name=name, arch="tiny", tokenizer_kind="byte",
                               context_length=512, eos_token_ids=[2], **kw)


class TestLocalPipeline:
    def test_chat_roundtrip_with_echo(self):
        async def main():
            card = byte_card()
            pipe = LocalPipeline(card, EchoTokenEngine())
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("echo-model", pipe, "both")
            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                {"model": "echo-model", "max_tokens": 500,
                 "messages": [{"role": "user", "content": "hello tpu"}]})
            assert status == 200
            content = json.loads(body)["choices"][0]["message"]["content"]
            # echo engine returns the rendered prompt text
            assert "hello tpu" in content
            # completions route too
            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/completions",
                {"model": "echo-model", "prompt": "abc", "max_tokens": 10})
            assert status == 200
            assert json.loads(body)["choices"][0]["text"] == "abc"
            await svc.stop()

        run(main())

    def test_stop_string_jails_and_finishes(self):
        async def main():
            card = byte_card()
            pipe = LocalPipeline(card, EchoTokenEngine())
            svc = await HttpService("127.0.0.1", 0).start()
            svc.models.add("echo-model", pipe, "completion")
            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/completions",
                {"model": "echo-model", "prompt": "hello STOP world",
                 "max_tokens": 100, "stop": ["STOP"]})
            assert status == 200
            choice = json.loads(body)["choices"][0]
            assert choice["text"] == "hello "
            assert choice["finish_reason"] == "stop"
            await svc.stop()

        run(main())


class TestKvRoutedDiscovery:
    def test_model_watcher_builds_kv_routed_pipeline(self):
        """kv_routed registration wires a KvRouter into the remote pipeline;
        the request lands on the worker holding the cached prefix."""
        async def main():
            from dynamo_tpu.engine.kv_cache import PageAllocator
            from dynamo_tpu.kv_router.publisher import KvEventPublisher
            from dynamo_tpu.kv_router.router import KvRouter

            plane = MemoryPlane()
            wrts, comps = {}, {}
            for wid in ("wa", "wb"):
                rt = await DistributedRuntime.create_local(plane, wid)
                await serve_llm_worker(rt, "ns", "backend", EchoTokenEngine(),
                                       card=byte_card())
                wrts[wid] = rt
                comps[wid] = rt.namespace("ns").component("backend")

            frt = await DistributedRuntime.create_local(plane, "front")
            svc = await HttpService("127.0.0.1", 0).start()
            routers = []

            async def make_router(component, client, card):
                r = await KvRouter(component, client,
                                   block_size=card.kv_page_size,
                                   scrape_interval_s=0.05).start()
                routers.append(r)
                return r

            watcher = await ModelWatcher(frt, svc.models,
                                         make_router=make_router).start()
            card = byte_card(kv_page_size=4)
            await register_model(frt.kv, "echo-model", "ns", "backend", card,
                                 model_type="chat", kv_routed=True)
            await asyncio.sleep(0.2)
            assert routers, "router was not built for kv_routed model"
            pipe = svc.models.chat["echo-model"]
            assert pipe.router is routers[0]

            # wb announces it holds the prompt's prefix pages
            prompt_text = "route me to the warm one"
            pre, _ = pipe.preprocessor.preprocess_chat(
                ChatCompletionRequest(model="echo-model", messages=[
                    {"role": "user", "content": prompt_text}]))
            alloc = PageAllocator(16, 4)
            parent = 0
            for i in range(len(pre.token_ids) // 4):
                pid = alloc.allocate()
                parent = alloc.seal(pid, parent,
                                    pre.token_ids[i * 4:(i + 1) * 4])
            await KvEventPublisher(comps["wb"], "wb").publish_allocator_events(
                alloc.drain_events())
            await asyncio.sleep(0.2)

            assert await routers[0].schedule(pre.token_ids) == "wb"
            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                {"model": "echo-model", "max_tokens": 400, "messages": [
                    {"role": "user", "content": prompt_text}]})
            assert status == 200
            assert prompt_text in \
                json.loads(body)["choices"][0]["message"]["content"]

            await watcher.stop()
            await svc.stop()
            for rt in list(wrts.values()) + [frt]:
                await rt.shutdown()

        run(main())


class TestDistributedServing:
    def test_echo_worker_via_registry_end_to_end(self):
        """frontend + model registry + remote echo worker over the in-memory
        control plane: the reference's full serve path without hardware."""
        async def main():
            plane = MemoryPlane()
            wrt = await DistributedRuntime.create_local(plane, "w1")
            card = byte_card()
            await serve_llm_worker(wrt, "ns", "backend", EchoTokenEngine(),
                                   card=card)

            frt = await DistributedRuntime.create_local(plane, "front")
            svc = await HttpService("127.0.0.1", 0).start()
            watcher = await ModelWatcher(frt, svc.models).start()
            await register_model(frt.kv, "echo-model", "ns", "backend", card,
                                 model_type="both")
            await asyncio.sleep(0.1)
            assert "echo-model" in svc.models.chat

            status, body = await request(
                "127.0.0.1", svc.port, "POST", "/v1/chat/completions",
                {"model": "echo-model", "max_tokens": 400,
                 "messages": [{"role": "user", "content": "over the wire"}]})
            assert status == 200
            content = json.loads(body)["choices"][0]["message"]["content"]
            assert "over the wire" in content

            # streaming path
            events = []
            async for ev, data in sse_events(
                    "127.0.0.1", svc.port, "/v1/chat/completions",
                    {"model": "echo-model", "stream": True, "max_tokens": 400,
                     "messages": [{"role": "user", "content": "abc"}]}):
                events.append(data)
            assert events[-1] == "[DONE]"
            text = "".join(
                json.loads(d)["choices"][0]["delta"].get("content") or ""
                for d in events[:-1] if d != "[DONE]")
            assert "abc" in text

            # deregistration removes the model live
            await unregister_model(frt.kv, "echo-model", "both")
            models = await list_registered_models(frt.kv)
            assert models == {}
            await asyncio.sleep(0.05)
            assert "echo-model" not in svc.models.chat

            await watcher.stop()
            await svc.stop()
            await frt.shutdown()
            await wrt.shutdown()

        run(main())
