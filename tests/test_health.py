"""Gray-failure detection unit tests (runtime/health.py).

The chaos A/B (tests/test_chaos.py fail_slow_storm) proves the plane
end to end; these pin the scorer's math one property at a time: the
robust MAD z-score, the min-evidence cold floor, enter/exit hysteresis,
watch-delete eviction, the hedge budget, and decision-timeline
determinism (the replay contract's unit-level twin).
"""
import pytest

from dynamo_tpu.runtime.health import HealthScorer, HedgeBudget


def mk(**kw):
    kw.setdefault("clock", lambda: 0.0)
    kw.setdefault("min_evidence", 3)
    kw.setdefault("enter_evals", 2)
    kw.setdefault("exit_evals", 2)
    return HealthScorer(**kw)


def feed(sc, latencies, n=4):
    """n samples per worker at the given per-worker latency."""
    for _ in range(n):
        for w, v in latencies.items():
            sc.observe(w, v)


# -- robust scoring ------------------------------------------------------------


def test_outlier_worker_scores_low_fleet_scores_high():
    sc = mk()
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "sick": 0.50})
    sc.evaluate(0.0)
    assert sc.score("sick") < 0.5 < sc.score("a")
    assert sc.zscore("sick") > sc.z_enter
    # the healthy majority is untouched by the outlier (median/MAD,
    # not mean/stddev: the sick worker cannot drag the baseline)
    assert sc.score("a") == sc.score("b") == sc.score("c") == 1.0


def test_median_baseline_resists_a_slow_clique():
    """Two of five workers degraded: the healthy three still define the
    baseline, so the clique stands out instead of normalizing itself."""
    sc = mk()
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "s1": 0.4, "s2": 0.5})
    sc.evaluate(0.0)
    assert sc.zscore("s1") > sc.z_enter
    assert sc.zscore("s2") > sc.z_enter
    assert sc.score("a") == 1.0


def test_no_quorum_no_condemnation():
    """Fewer than 3 warm workers: no fleet baseline, everyone healthy."""
    sc = mk()
    feed(sc, {"a": 0.05, "sick": 5.0})
    for t in range(5):
        assert sc.evaluate(float(t)) == []
    assert sc.score("sick") == 1.0
    assert not sc.is_slow("sick")


def test_min_evidence_floor_never_condemns_cold():
    """A cold worker (few samples — fresh restart, still compiling) is
    exempt no matter how slow its first observations are."""
    sc = mk(min_evidence=8)
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05}, n=10)
    sc.observe("cold", 9.0)   # 1 sample << min_evidence
    for t in range(5):
        sc.evaluate(float(t))
    assert sc.score("cold") == 1.0
    assert not sc.is_slow("cold")
    # once warm, the same latency condemns it
    feed(sc, {"cold": 9.0}, n=8)
    sc.evaluate(10.0)
    sc.evaluate(11.0)
    assert sc.is_slow("cold")


def test_link_err_evidence_inflates_z():
    """A persistently underestimated link (gray NIC) adds to the
    worker's effective z even when its service latency looks typical."""
    sc = mk(z_enter=1.0, z_exit=0.5)
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05})
    sc.observe_link_err("c", 1.0)
    sc.evaluate(0.0)
    assert sc.zscore("c") == pytest.approx(sc.err_weight)
    assert sc.zscore("a") == 0.0


# -- hysteresis ----------------------------------------------------------------


def test_enter_needs_consecutive_evals():
    sc = mk(enter_evals=3)
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "sick": 0.5})
    assert sc.evaluate(0.0) == []          # streak 1
    assert sc.evaluate(1.0) == []          # streak 2
    events = sc.evaluate(2.0)              # streak 3: trip
    assert [e["event"] for e in events] == ["slow_enter"]
    assert events[0]["worker"] == "sick"
    assert sc.is_slow("sick")


def test_one_spike_flips_nothing():
    """The streak resets when z dips back under z_enter mid-streak."""
    sc = mk(enter_evals=2)
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "w": 0.5})
    assert sc.evaluate(0.0) == []          # streak 1
    # recovery samples pull the EWMA back toward the fleet before the
    # second strike lands
    feed(sc, {"w": 0.05}, n=12)
    assert sc.evaluate(1.0) == []          # streak broken
    feed(sc, {"w": 0.5}, n=4)
    assert sc.evaluate(2.0) == []          # streak 1 again, not 2
    assert not sc.is_slow("w")


def test_exit_hysteresis_and_recovery():
    sc = mk()
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "sick": 0.5})
    sc.evaluate(0.0)
    sc.evaluate(1.0)
    assert sc.is_slow("sick")
    feed(sc, {"sick": 0.05}, n=20)         # EWMA converges back
    assert sc.evaluate(2.0) == []          # exit streak 1
    events = sc.evaluate(3.0)              # exit streak 2: recover
    assert [e["event"] for e in events] == ["slow_exit"]
    assert not sc.is_slow("sick")
    assert sc.slow_workers() == []


def test_hysteresis_requires_exit_below_enter():
    with pytest.raises(ValueError):
        HealthScorer(z_enter=2.0, z_exit=2.0)


# -- eviction + determinism ----------------------------------------------------


def test_forget_evicts_all_state():
    """Watch-delete hook: a reused worker name starts cold — it must not
    inherit a corpse's EWMA, SLOW flag, or streaks."""
    sc = mk()
    feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05, "sick": 0.5})
    sc.evaluate(0.0)
    sc.evaluate(1.0)
    assert sc.is_slow("sick")
    sc.forget("sick")
    assert not sc.is_slow("sick")
    assert sc.score("sick") == 1.0
    assert sc.evidence("sick") == 0
    assert "sick" not in sc.snapshot()["workers"]


def test_same_stream_same_timeline():
    """Scoring is a pure function of the observation stream + clock:
    the replay contract (fail_slow_ab timeline_replay_ok), unit-sized."""
    def run():
        sc = mk()
        for t in range(6):
            feed(sc, {"a": 0.05, "b": 0.05, "c": 0.05,
                      "sick": 0.5 if t < 4 else 0.05}, n=2)
            sc.evaluate(float(t))
        return sc.timeline
    assert run() == run()


# -- hedge budget --------------------------------------------------------------


def test_hedge_budget_burst_then_denial():
    b = HedgeBudget(budget_frac=0.5, burst=2)
    # no requests seen yet: only the burst allowance
    assert b.try_fire("std")
    assert b.try_fire("std")
    assert not b.try_fire("std")
    # volume grows the budget: 4 requests * 0.5 + 2 = 4 total
    for _ in range(4):
        b.on_request("std")
    assert b.try_fire("std")
    assert b.try_fire("std")
    assert not b.try_fire("std")


def test_hedge_budget_is_per_class():
    b = HedgeBudget(budget_frac=0.0, burst=1)
    assert b.try_fire("interactive")
    assert not b.try_fire("interactive")
    assert b.try_fire("batch")             # separate class, own burst
    snap = b.snapshot()
    assert snap["fired"] == {"interactive": 1, "batch": 1}
